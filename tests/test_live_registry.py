"""Live registries (ISSUE PR 16): epoch-versioned in-place updates.

The load-bearing contracts:

- **Graph edge folds are bitwise ≡ re-registration.**  A registered
  ``GraphSystem`` retains its SJLT and folded sketch; absorbing an edge
  batch through ``fold_graph_edges`` lands the exact bits a from-scratch
  registration of the merged graph computes (0/1 adjacency × ±2⁻¹ SJLT
  values make every partial sum exact dyadic — order-invariant).
- **LS row appends/downdates are exact ``apply_slice`` deltas** into the
  retained ``S·A`` (allclose to fresh registration; the QR re-runs on
  the small (s, n) sketch only).  FJLT-backed systems have no columnwise
  partial rule and refuse live deltas with a structured UnsupportedError.
- **In-flight work stays bitwise on the version it admitted under**:
  ``Entry.entity`` pins the version object at validation, updates mint
  NEW immutable objects, and the superseded bits keep serving whatever
  already entered the queue.
- **Epoch pins are honest**: a request carrying ``registry_epoch`` for a
  retired (or unminted) version gets a code-116 ``RegistryEpochError``
  envelope with both epochs — never silently-new bits.
- **``update`` ops apply exactly once, in admission order** — unique
  coalesce keys mean they never batch and never solo-retry, so the queue
  order IS the epoch order.
"""

import numpy as np
import pytest

from libskylark_tpu import serve, telemetry
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.graph.graph import SimpleGraph
from libskylark_tpu.serve.registry import Registry
from libskylark_tpu.utils import exceptions as ex

pytestmark = pytest.mark.serve

# A ring covers every vertex, so the held-out chords fold into an
# unchanged vertex interning — the precondition for bitwise fold ≡
# re-registration (with_edges extends edges over the EXISTING id map).
N_V = 24
RING = [(i, (i + 1) % N_V) for i in range(N_V)]
CHORDS = [(i, (i + 5) % N_V) for i in range(0, N_V, 3)]

M, N = 48, 6
_rng = np.random.default_rng(11)
A_LS = _rng.standard_normal((M, N))
ROWS = _rng.standard_normal((4, N))
B = _rng.standard_normal(M)


def _graph_registry(edges, seed=5, k=4):
    reg = Registry()
    gsys = reg.register_graph(
        "g", SimpleGraph(edges), k=k, context=SketchContext(seed=seed)
    )
    return reg, gsys


def _ls_registry(A, *, sketch_type="SJLT", capacity=M + 8, seed=3):
    reg = Registry()
    system = reg.register_system(
        "sys", A, context=SketchContext(seed=seed),
        sketch_type=sketch_type, sketch_size=32, capacity=capacity,
    )
    return reg, system


def _server(seed=1):
    srv = serve.Server(
        serve.ServeParams(warm_start=False, prime=False), seed=seed
    )
    srv.registry.register_system(
        "sys", A_LS, context=SketchContext(seed=9),
        sketch_type="SJLT", sketch_size=32, capacity=M + 8,
    )
    return srv


# ---------------------------------------------------------------------------
# graph folds: the bitwise contract


def test_graph_fold_bitwise_equals_reregistration():
    reg, base = _graph_registry(RING)
    new, rec = reg.fold_graph_edges("g", CHORDS)
    _, ref = _graph_registry(RING + CHORDS)

    assert rec["kind"] == "graph_fold" and rec["edges"] == len(CHORDS)
    assert new is reg.graphs["g"] and new.epoch == 2
    # the retained-sketch fold lands the exact bits a from-scratch
    # registration of the merged graph computes
    assert np.array_equal(np.asarray(new._sa), np.asarray(ref._sa))
    assert np.array_equal(new.X, ref.X)
    assert np.array_equal(new.lam, ref.lam)
    # the superseded version object is untouched (in-flight bits);
    # volume counts directed arcs, two per undirected edge
    assert base.G.volume == 2 * len(RING) and base.epoch == 1
    assert new.G.volume == 2 * (len(RING) + len(CHORDS))
    assert [r["kind"] for r in reg.epoch_log] == ["register", "graph_fold"]


def test_graph_refold_of_held_edges_is_a_noop():
    reg, g0 = _graph_registry(RING)
    # an already-held edge and its reverse: both collapse to nothing
    new, rec = reg.fold_graph_edges("g", [RING[0], (1, 0)])
    assert rec["edges"] == 0
    assert new._sa is g0._sa  # no refold, arrays carried by reference
    assert np.array_equal(new.X, g0.X)


# ---------------------------------------------------------------------------
# LS systems: append / downdate deltas


def test_ls_append_matches_fresh_registration():
    reg, old = _ls_registry(A_LS)
    new, rec = reg.append_system_rows("sys", ROWS)
    assert rec["kind"] == "row_append" and rec["rows"] == 4
    assert new.m == M + 4 and old.m == M  # superseded version frozen
    assert new.epoch == 2 and reg.systems["sys"] is new

    # reference: fresh registration of the merged matrix with the SAME
    # sketch object (same capacity domain)
    ref = Registry().register_system(
        "sys", np.vstack([A_LS, ROWS]), context=SketchContext(seed=0),
        sketch=old.S, capacity=M + 8,
    )
    assert np.allclose(np.asarray(new.SA), np.asarray(ref.SA))
    assert np.allclose(np.asarray(new.R), np.asarray(ref.R))

    # appends past the reserved capacity refuse with a structured error
    with pytest.raises(ex.InvalidParameters):
        reg.append_system_rows("sys", np.ones((20, N)))


def test_ls_downdate_retires_rows_exactly_once():
    reg, old = _ls_registry(A_LS)
    new, rec = reg.downdate_system_rows("sys", [3, 17])
    assert rec["kind"] == "row_downdate" and rec["retired"] == 2
    assert new.retired == frozenset({3, 17}) and old.retired == frozenset()

    A_zeroed = A_LS.copy()
    A_zeroed[[3, 17]] = 0.0
    ref = Registry().register_system(
        "sys", A_zeroed, context=SketchContext(seed=0),
        sketch=old.S, capacity=M + 8,
    )
    assert np.allclose(np.asarray(new.SA), np.asarray(ref.SA))
    # retiring an already-retired row is a caller error, not a no-op
    with pytest.raises(ex.InvalidParameters):
        reg.downdate_system_rows("sys", [3])


def test_fjlt_backed_system_refuses_live_append():
    reg, _ = _ls_registry(A_LS, sketch_type="FJLT")
    with pytest.raises(ex.UnsupportedError):
        reg.append_system_rows("sys", ROWS)


# ---------------------------------------------------------------------------
# epoch pinning: in-flight bits and the code-116 fence


def test_inflight_request_pinned_to_admitted_epoch_bitwise():
    live, ref = _server(), _server()
    # admit BEFORE the worker starts, then move the registry head
    fut = live.submit(serve.make_request("ls_solve", system="sys", b=B))
    live.registry.append_system_rows("sys", ROWS)
    live.start()
    got = fut.result()
    live.stop()

    ref.start()
    want = ref.call(serve.make_request("ls_solve", system="sys", b=B))
    ref.stop()

    assert got["ok"] and want["ok"]
    # bitwise: the queued request served the version it admitted under
    assert np.array_equal(
        np.asarray(got["result"]), np.asarray(want["result"])
    )
    assert got["trace"]["registry_epoch"] == 1
    assert live.registry.get_system("sys").epoch == 2


def test_retired_epoch_pin_gets_code_116_envelope():
    srv = _server().start()
    try:
        ok = srv.call(
            op="ls_solve", system="sys", b=B, registry_epoch=1
        )
        assert ok["ok"]  # pinning the CURRENT epoch is honored
        srv.registry.append_system_rows("sys", ROWS)
        resp = srv.call(
            op="ls_solve", system="sys", b=B, registry_epoch=1
        )
    finally:
        srv.stop()
    assert not resp["ok"]
    err = resp["error"]
    assert err["code"] == 116
    assert err["requested"] == 1 and err["current"] == 2
    assert err["entity"] == "sys"
    with pytest.raises(ex.RegistryEpochError):
        serve.raise_for_error(resp)


# ---------------------------------------------------------------------------
# the update op: served mutations, exactly once, in admission order


def test_update_op_applies_exactly_once_in_admission_order():
    srv = _server(seed=2)
    srv.registry.register_graph(
        "g", SimpleGraph(RING), k=4, context=SketchContext(seed=5)
    )
    # three mutations queued BEFORE the worker starts: each must apply
    # exactly once, in admission order, never coalescing
    f1 = srv.submit({"op": "update", "graph": "g", "edges": CHORDS})
    f2 = srv.submit({"op": "update", "system": "sys",
                     "append": ROWS.tolist()})
    f3 = srv.submit({"op": "update", "system": "sys", "drop": [0]})
    srv.start()
    r1, r2, r3 = f1.result(), f2.result(), f3.result()
    srv.stop()

    assert r1["ok"] and r2["ok"] and r3["ok"]
    assert r1["result"]["kind"] == "graph_fold"
    assert r1["result"]["edges"] == len(CHORDS)
    assert r2["result"]["kind"] == "row_append"
    assert r2["result"]["rows"] == 4
    assert r3["result"]["kind"] == "row_downdate"
    # 2 registrations then 3 updates: the queue order IS the epoch order
    assert [r["result"]["epoch"] for r in (r1, r2, r3)] == [3, 4, 5]
    assert srv.registry.epoch == 5
    assert srv.registry.get_system("sys").m == M + 4
    assert srv.registry.get_system("sys").retired == frozenset({0})
    assert not any(r["trace"]["coalesced"] for r in (r1, r2, r3))


def test_update_op_validates_targets_at_the_door():
    srv = _server(seed=3)
    srv.start()
    try:
        both = srv.call(op="update", system="sys", append=[[0.0] * N],
                        drop=[1])
        neither = srv.call(op="update")
        unknown = srv.call(op="update", graph="nope", edges=[(0, 1)])
    finally:
        srv.stop()
    for resp in (both, neither, unknown):
        assert not resp["ok"] and resp["error"]["code"] == 102


# ---------------------------------------------------------------------------
# model updates (server-side API) and the telemetry fold


def test_update_model_center_deltas_and_swap():
    from libskylark_tpu.ml.kernels import GaussianKernel
    from libskylark_tpu.ml.model import KernelModel

    rng = np.random.default_rng(8)
    km = KernelModel(
        GaussianKernel(12, sigma=1.1),
        rng.standard_normal((24, 12)),
        rng.standard_normal((24, 3)),
    )
    reg = Registry()
    reg.register_model("krr", km)
    xq = rng.standard_normal((3, 12))
    base = np.asarray(km.predict(xq))

    X_new = rng.standard_normal((2, 12))
    A_new = rng.standard_normal((2, 3))
    m2, rec = reg.update_model("krr", append=(X_new, A_new))
    assert rec["kind"] == "model_update" and rec["appended"] == 2
    assert np.asarray(m2.X_train).shape[0] == 26
    # predict is linear in the center rows: the delta is exact
    delta = KernelModel(km.kernel, X_new, A_new)
    assert np.allclose(
        np.asarray(m2.predict(xq)), base + np.asarray(delta.predict(xq))
    )

    m3, rec = reg.update_model("krr", drop=[24, 25])
    assert rec["dropped"] == 2
    assert np.allclose(np.asarray(m3.predict(xq)), base)

    _, rec = reg.update_model("krr", model=km)
    assert rec["swapped"] is True
    assert reg.epoch == 4
    with pytest.raises(ex.InvalidParameters):
        reg.update_model("krr", model=km, drop=[0])


def test_registry_epoch_counters_fold_into_snapshot(monkeypatch):
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.REGISTRY.reset()
    reg, _ = _graph_registry(RING)
    reg.fold_graph_edges("g", CHORDS)
    ls_reg, _ = _ls_registry(A_LS)
    ls_reg.append_system_rows("sys", ROWS)
    snap = telemetry.snapshot()
    telemetry.REGISTRY.reset()
    assert snap["registry"]["epoch.bumps"] == 4
    assert snap["registry"]["epoch.register"] == 2
    assert snap["registry"]["epoch.graph_fold"] == 1
    assert snap["registry"]["epoch.row_append"] == 1
