"""Fleet-scale serving (ISSUE PR 13): device-parallel dispatch,
replicated batcher workers, and the profile-aware front-door router.

The load-bearing contracts:

- **Sharded dispatch is bitwise-identical to single-device dispatch.**
  The first dispatch of every sharded program is a parity probe that
  runs BOTH routes on the live batch and compares bits; a match serves
  sharded thereafter, a mismatch tombstones the program — either way
  the response bits equal the single-device path's.
- **K workers ≡ 1 worker, bitwise.**  Pinned workers drain the same
  admission queue through the same per-slot-pure executors; worker
  count may change scheduling, never bits.
- **2-replica routed ≡ single-worker serial, bitwise** for LS-solve
  and KRR-predict across rung boundaries (same-seed registries).
- **Placement is a pure function** of the frozen load reports
  (affinity → depth → profiled throughput → name).
- **Membership is fenced**: signature mismatch = 109 at join,
  heartbeat loss = ejection + re-placement, 114 only when no
  placeable replica remains, fleet saturation = the same 112 envelope
  a single server sheds with.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from libskylark_tpu import serve, telemetry
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.ml.kernels import GaussianKernel
from libskylark_tpu.ml.model import FeatureMapModel, KernelModel
from libskylark_tpu.serve import dispatch, protocol
from libskylark_tpu.sketch.rft import GaussianRFT
from libskylark_tpu.utils import exceptions as ex

pytestmark = pytest.mark.fleet

M, N = 64, 5
_rng = np.random.default_rng(77)
A = _rng.standard_normal((M, N))
RHS = [_rng.standard_normal(M) for _ in range(12)]
XQ = [_rng.standard_normal(12) for _ in range(12)]


def _params(max_coalesce=16, workers=1, **kw):
    return serve.ServeParams(
        max_coalesce=max_coalesce, warm_start=False, prime=False,
        workers=workers, **kw
    )


def _feature_map_model():
    S = GaussianRFT(12, 32, SketchContext(seed=5), sigma=1.2)
    W = np.random.default_rng(7).standard_normal((32, 3))
    return FeatureMapModel([S], W, scale_maps=True)


def _kernel_model():
    rng = np.random.default_rng(8)
    return KernelModel(
        GaussianKernel(12, sigma=1.1),
        rng.standard_normal((24, 12)),
        rng.standard_normal((24, 3)),
    )


def _replica(max_coalesce=16, workers=1, seed=42, **kw):
    """A full replica: same-seed registry every time, so bitwise
    comparisons across replicas/servers are meaningful."""
    srv = serve.Server(_params(max_coalesce, workers, **kw), seed=seed)
    srv.registry.register_system("sys", A, context=SketchContext(seed=9))
    srv.registry.register_model("fm", _feature_map_model())
    srv.registry.register_model("krr", _kernel_model())
    return srv


def _requests():
    """LS + both predict kinds, counts that straddle the 8→16 rung."""
    return (
        [serve.make_request("ls_solve", system="sys", b=b) for b in RHS[:10]]
        + [serve.make_request("predict", model="fm", x=x) for x in XQ[:10]]
        + [serve.make_request("predict", model="krr", x=x) for x in XQ[:10]]
    )


def _serial_reference():
    srv = _replica(max_coalesce=1)
    srv.start()
    results = [srv.call(r) for r in _requests()]
    srv.stop()
    return results


# ---------------------------------------------------------------------------
# placement: pure, deterministic


def test_placement_key_mirrors_coalescing_identity():
    assert protocol.placement_key(
        {"op": "ls_solve", "system": "sys"}
    ) == "ls:sys"
    assert protocol.placement_key(
        {"op": "predict", "model": "m"}
    ) == "predict:m:float64"
    assert protocol.placement_key(
        {"op": "predict", "model": "m", "dtype": "float32"}
    ) == "predict:m:float32"
    assert protocol.placement_key({"op": "ping"}) == "ping"


def _report(depth, cap=8, tput=None, profile=None):
    rep = {"queue_depth": depth, "max_queue": cap, "throughput": {}}
    if tput is not None:
        rep["throughput"]["ls:sys"] = {"rows_per_s": tput}
    if profile is not None:
        rep["profiles"] = {"any": {"rows_per_s": profile}}
    return rep


def test_choose_replica_is_pure_and_deterministic():
    members = {
        "b": {"placeable": True, "report": _report(3)},
        "a": {"placeable": True, "report": _report(3)},
        "c": {"placeable": True, "report": _report(1)},
    }
    # lowest live queue depth wins; dict order must not matter
    assert serve.choose_replica("ls:sys", members, {}) == "c"
    flipped = dict(reversed(list(members.items())))
    assert serve.choose_replica("ls:sys", flipped, {}) == "c"
    # depth tie: measured per-key throughput breaks it
    members["a"]["report"] = _report(1, tput=100.0)
    assert serve.choose_replica("ls:sys", members, {}) == "a"
    # the policy profile prior stands in when the key was never served
    members["b"]["report"] = _report(1, profile=500.0)
    assert serve.choose_replica("ls:sys", members, {}) == "b"
    # throughput tie all around: lexicographic name, still deterministic
    fresh = {
        n: {"placeable": True, "report": _report(2)} for n in ("y", "x", "z")
    }
    assert serve.choose_replica("ls:sys", fresh, {}) == "x"
    # affinity (coalescing) beats a better-scored stranger
    assert serve.choose_replica("ls:sys", members, {"ls:sys": "c"}) == "c"
    # ... but not a saturated or unplaceable one
    members["c"]["report"] = _report(8)
    assert serve.choose_replica("ls:sys", members, {"ls:sys": "c"}) == "b"
    members["c"]["report"] = _report(1)
    members["c"]["placeable"] = False
    assert serve.choose_replica("ls:sys", members, {"ls:sys": "c"}) == "b"
    # every placeable member saturated -> None (the caller sheds 112)
    for m in members.values():
        m["report"] = _report(8)
    assert serve.choose_replica("ls:sys", members, {}) is None


# ---------------------------------------------------------------------------
# device-parallel dispatch: gates + the bitwise probe contract


def test_shard_gates(monkeypatch):
    # lane-uniform feasibility: shard width must stay a multiple of 8
    assert not dispatch.supported(8, 2)
    assert dispatch.supported(16, 2)
    assert not dispatch.supported(16, 3)
    assert not dispatch.supported(16, 4)
    assert dispatch.supported(32, 4)
    assert not dispatch.supported(32, 0)
    # mode "0" disables even enormous dispatches
    monkeypatch.setenv("SKYLARK_SERVE_SHARD", "0")
    assert dispatch.shard_devices(32, 1e12) is None
    # auto honors the amortization floor
    monkeypatch.setenv("SKYLARK_SERVE_SHARD", "")
    assert dispatch.shard_devices(32, 1.0) is None
    assert dispatch.shard_devices(32, 1e12) is not None
    # force mode skips worthwhile() but never supported()
    monkeypatch.setenv("SKYLARK_SERVE_SHARD", "1")
    devs = dispatch.shard_devices(32, 1.0)
    assert devs is not None and len(devs) == 4  # largest feasible split
    assert dispatch.shard_devices(8, 1e12) is None
    # the env floor is respected in auto mode
    monkeypatch.setenv("SKYLARK_SERVE_SHARD", "")
    monkeypatch.setenv("SKYLARK_SERVE_SHARD_MIN_FLOPS", "10")
    assert dispatch.shard_devices(16, 100.0) is not None


def test_sharded_dispatch_bitwise_and_probed(monkeypatch):
    """Forced sharding must change NO bits: the probe runs both routes
    on the first batch of each program and the executor serves the
    reference bits; subsequent batches ride the verified program."""
    monkeypatch.setenv("SKYLARK_SERVE_SHARD", "1")
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.REGISTRY.reset()
    dispatch.clear_cache()
    serial = _serial_reference()  # max_coalesce=1 -> kb=8, never sharded

    srv = _replica(max_coalesce=16)
    futures = [srv.submit(r) for r in _requests()]
    srv.start()
    routed = [f.result() for f in futures]
    srv.stop()
    dispatch.clear_cache()
    counters = telemetry.REGISTRY.snapshot()["counters"]
    telemetry.REGISTRY.reset()

    assert all(r["ok"] for r in serial + routed)
    for s, c in zip(serial, routed):
        assert (np.asarray(s["result"]) == np.asarray(c["result"])).all()
    # every coalesced program ran its one-time parity probe ...
    kinds = {
        e["kind"]
        for r in routed
        for e in r["trace"]["events"]
        if "shard" in e.get("kind", "")
    }
    assert "sharded_probe" in kinds
    # ... and the LS probe (FJLT at this scale) verifies, so at least
    # one program carries a recorded verdict
    assert (
        counters.get("serve.sharded_verified", 0)
        + counters.get("serve.sharded_rejected", 0)
    ) >= 1


def test_shard_auto_mode_stays_single_device_at_small_scale(monkeypatch):
    """Unset env: the amortization gate keeps test-scale batches on the
    single-device path — the PR-10 executor, no probes, no programs."""
    monkeypatch.delenv("SKYLARK_SERVE_SHARD", raising=False)
    monkeypatch.delenv("SKYLARK_SERVE_SHARD_MIN_FLOPS", raising=False)
    dispatch.clear_cache()
    srv = _replica(max_coalesce=16)
    futures = [
        srv.submit(serve.make_request("ls_solve", system="sys", b=b))
        for b in RHS[:10]
    ]
    srv.start()
    results = [f.result() for f in futures]
    srv.stop()
    assert all(r["ok"] for r in results)
    assert not dispatch._PROGRAMS  # nothing was ever built
    for r in results:
        assert all(
            "shard" not in e.get("kind", "") for e in r["trace"]["events"]
        )


# ---------------------------------------------------------------------------
# replicated workers


def test_multi_worker_bitwise_identical_to_single():
    def run(workers):
        srv = _replica(max_coalesce=4, workers=workers)
        srv.start()
        futures = [srv.submit(r) for r in _requests()]
        results = [f.result() for f in futures]
        report = srv.load_report()
        srv.stop()
        return results, report

    one, _ = run(1)
    two, report = run(2)
    assert all(r["ok"] for r in one + two)
    for s, c in zip(one, two):
        assert (np.asarray(s["result"]) == np.asarray(c["result"])).all()
    assert report["workers"] == 2
    # the load report carries per-key measured throughput for placement
    assert "ls:sys" in report["throughput"]
    assert report["throughput"]["ls:sys"]["requests"] == 10
    assert report["census"]["systems"] == ["sys"]
    assert isinstance(report["signature"], int)


def test_multi_worker_prime_covers_every_pinned_device():
    srv = serve.Server(
        serve.ServeParams(
            max_coalesce=8, warm_start=False, prime=True, workers=2
        ),
        seed=3,
    )
    srv.registry.register_system("sys", A, context=SketchContext(seed=9))
    srv.start()
    primed = list(srv.primed)
    r = srv.call(op="ls_solve", system="sys", b=RHS[0])
    srv.stop()
    assert r["ok"]
    assert any(p.startswith("system:sys") for p in primed)


# ---------------------------------------------------------------------------
# the front-door router


def test_two_replica_routed_bitwise_equals_single_worker_serial():
    serial = _serial_reference()
    r1, r2 = _replica().start(), _replica().start()
    router = serve.Router()
    assert router.join("r1", server=r1)["epoch"] == 1
    assert router.join("r2", server=r2)["epoch"] == 2
    futures = [router.submit(r) for r in _requests()]
    routed = [f.result() for f in futures]
    fleet = router.fleet_report()
    router.stop()
    r1.stop()
    r2.stop()

    assert all(r["ok"] for r in serial + routed)
    for s, c in zip(serial, routed):
        assert (np.asarray(s["result"]) == np.asarray(c["result"])).all()
    # placement rode affinity: each key pinned to exactly one replica,
    # and every response is stamped with its placement + fleet epoch
    by_key: dict = {}
    for req, resp in zip(_requests(), routed):
        by_key.setdefault(protocol.placement_key(req), set()).add(
            resp["trace"]["replica"]
        )
        assert resp["trace"]["fleet_epoch"] == 2
    assert all(len(replicas) == 1 for replicas in by_key.values())
    assert fleet["epoch"] == 2 and len(fleet["members"]) == 2


def test_join_signature_mismatch_code_109():
    r1 = _replica().start()
    router = serve.Router()
    router.join("r1", server=r1)
    odd = serve.Server(_params(), seed=42)
    odd.registry.register_system("other", A, context=SketchContext(seed=9))
    odd.start()
    with pytest.raises(ex.WorldMismatchError) as ei:
        router.join("odd", server=odd)
    assert ei.value.code == 109
    fleet = router.fleet_report()
    assert set(fleet["members"]) == {"r1"} and fleet["epoch"] == 1
    router.stop()
    odd.stop()
    r1.stop()


def test_heartbeat_eject_114_and_replacement_on_survivors():
    r1, r2 = _replica().start(), _replica().start()
    router = serve.Router(serve.RouterParams(heartbeat_timeout_s=5.0))
    router.join("r1", server=r1)
    router.join("r2", server=r2)
    # pin the LS key's affinity to whichever replica places first
    first = router.call(op="ls_solve", system="sys", b=RHS[0])
    assert first["ok"]
    pinned = first["trace"]["replica"]
    lost, survivor = (
        ("r1", r2) if pinned == "r1" else ("r2", r1)
    )
    (r1 if lost == "r1" else r2).stop()  # worker dies mid-fleet

    now = time.monotonic()
    assert router.poll_once(now=now)[lost] is False  # fenced immediately
    alive = router.poll_once(now=now + 10.0)  # past the timeout: ejected
    assert set(alive) == {"r1", "r2"} - {lost}
    fleet = router.fleet_report()
    assert lost not in fleet["members"]
    assert fleet["epoch"] == 3  # two joins + one eject
    # the dead replica's keys re-place transparently on the survivor
    results = [
        router.call(op="ls_solve", system="sys", b=b) for b in RHS[:4]
    ]
    assert all(r["ok"] for r in results)
    assert {r["trace"]["replica"] for r in results} == set(alive)

    # the last replica dies too: 114 reaches the caller, structured.
    # (keep the injected clock moving forward past the survivor's
    # refreshed heartbeat at now+10)
    survivor.stop()
    router.poll_once(now=now + 20.0)
    resp = router.call(op="ls_solve", system="sys", b=RHS[0])
    assert not resp["ok"] and resp["error"]["code"] == 114
    with pytest.raises(ex.ReplicaLostError):
        serve.raise_for_error(resp)
    router.stop()


def test_fleet_saturation_sheds_code_112():
    r1 = _replica().start()
    router = serve.Router()
    router.join("r1", server=r1)
    with router._lock:  # freeze a saturated report, as a heartbeat would
        router._members["r1"].report = _report(8, cap=8)
    resp = router.call(op="ls_solve", system="sys", b=RHS[0])
    assert not resp["ok"] and resp["error"]["code"] == 112
    with pytest.raises(ex.AdmissionError):
        serve.raise_for_error(resp)
    router.stop()
    r1.stop()


def test_join_is_placeable_only_after_prime_and_start():
    """Zero-downtime rollout: an unstarted (unprimed) replica may join
    but draws no traffic until its worker loop is up — and start()
    primes BEFORE spawning workers, so placeable implies warm."""
    warm = _replica().start()
    cold = serve.Server(
        serve.ServeParams(warm_start=False, prime=True), seed=42
    )
    cold.registry.register_system("sys", A, context=SketchContext(seed=9))
    cold.registry.register_model("fm", _feature_map_model())
    cold.registry.register_model("krr", _kernel_model())
    router = serve.Router()
    router.join("warm", server=warm)
    rec = router.join("cold", server=cold)
    assert rec["placeable"] is False
    r = router.call(op="ls_solve", system="sys", b=RHS[0])
    assert r["ok"] and r["trace"]["replica"] == "warm"

    cold.start()  # primes the ladder, THEN spawns the worker
    assert cold.primed
    router.poll_once()
    assert router.fleet_report()["members"]["cold"]["placeable"]
    # drain the affinity pin: a fresh key may now land on cold
    with router._lock:
        router._affinity.clear()
        router._members["warm"].report = _report(8, cap=8)
    r = router.call(op="ls_solve", system="sys", b=RHS[1])
    assert r["ok"] and r["trace"]["replica"] == "cold"
    router.stop()
    warm.stop()
    cold.stop()


# ---------------------------------------------------------------------------
# HTTP: keep-alive client, /fleet + /join endpoints, skylark-top


def _http_server(srv):
    httpd = serve.serve_http(srv, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, port = httpd.server_address[:2]
    return httpd, f"http://{host}:{port}"


def test_client_keepalive_connection_reuse(monkeypatch):
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.REGISTRY.reset()
    srv = _replica().start()
    httpd, url = _http_server(srv)
    try:
        client = serve.Client(url=url)
        for b in RHS[:3]:
            assert client.ls_solve("sys", b, check=True)
        health = client.healthz()
        client.close()
    finally:
        httpd.shutdown()
        srv.stop()
    counters = telemetry.REGISTRY.snapshot()["counters"]
    telemetry.REGISTRY.reset()
    # one TCP connect, then reuse (HTTP/1.1 keep-alive end to end)
    assert counters.get("serve.client_conn_fresh") == 1
    assert counters.get("serve.client_conn_reused", 0) >= 3
    assert health["ok"] and "load" in health
    assert health["load"]["signature"] == _replica().signature()


def test_router_http_front_door_join_fleet_and_placement():
    replica = _replica().start()
    rep_httpd, rep_url = _http_server(replica)
    router = serve.Router()
    front_httpd, front_url = _http_server(router)
    try:
        # a replica announces itself over POST /join
        req = urllib.request.Request(
            front_url + "/join",
            data=json.dumps({"name": "r1", "url": rep_url}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            rec = json.loads(r.read().decode())
        assert rec["ok"] and rec["placeable"]
        # GET /fleet on the front door shows the membership table
        with urllib.request.urlopen(front_url + "/fleet", timeout=10) as r:
            fleet = json.loads(r.read().decode())
        assert "r1" in fleet["members"] and fleet["epoch"] == 1
        # ... and on a plain replica, its own load report
        with urllib.request.urlopen(rep_url + "/fleet", timeout=10) as r:
            load = json.loads(r.read().decode())
        assert load["worker_alive"] and "throughput" in load
        # POST / to the front door routes through the HTTP replica,
        # bitwise equal to asking the replica directly
        front = serve.Client(url=front_url)
        direct = serve.Client(url=rep_url)
        via_router = front.ls_solve("sys", RHS[0], check=True)
        straight = direct.ls_solve("sys", RHS[0], check=True)
        assert via_router == straight
        # a signature-mismatched joiner is rejected with a 109 envelope
        odd = serve.Server(_params(), seed=42)
        odd.registry.register_system(
            "other", A, context=SketchContext(seed=9)
        )
        odd.start()
        odd_httpd, odd_url = _http_server(odd)
        try:
            req = urllib.request.Request(
                front_url + "/join",
                data=json.dumps({"name": "odd", "url": odd_url}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            body = json.loads(ei.value.read().decode())
            assert ei.value.code == 409
            assert body["error"]["code"] == 109
        finally:
            odd_httpd.shutdown()
            odd.stop()
    finally:
        front_httpd.shutdown()
        rep_httpd.shutdown()
        router.stop()
        replica.stop()


def test_skylark_top_renders_fleet_table(capsys):
    from libskylark_tpu.cli.top import main

    r1, r2 = _replica().start(), _replica().start()
    h1, u1 = _http_server(r1)
    h2, u2 = _http_server(r2)
    try:
        r1.call(op="ls_solve", system="sys", b=RHS[0])
        assert main(["--url", u1, "--url", u2, "--once"]) == 0
        out = capsys.readouterr().out
        assert "fleet (2 replicas)" in out
        assert "replica" in out and "queue" in out and "heartbeat" in out
        assert u1 in out and u2 in out
        # single-url mode keeps the PR-12 detail view
        assert main(["--url", u1, "--once"]) == 0
        out = capsys.readouterr().out
        assert "serve " + u1 in out
        assert "p50" in out
    finally:
        h1.shutdown()
        h2.shutdown()
        r1.stop()
        r2.stop()


def test_router_counters_fold_into_snapshot(monkeypatch):
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.REGISTRY.reset()
    r1 = _replica().start()
    router = serve.Router()
    router.join("r1", server=r1)
    for b in RHS[:3]:
        assert router.call(op="ls_solve", system="sys", b=b)["ok"]
    snap = telemetry.snapshot()
    router.stop()
    r1.stop()
    telemetry.REGISTRY.reset()
    assert snap["router"]["placements"] == 3
    assert snap["router"]["affinity_hits"] == 2
    assert snap["router"]["joins"] == 1
    assert 0.0 <= snap["router"]["affinity_ratio"] <= 1.0


# ---------------------------------------------------------------------------
# serve through change (ISSUE PR 16): races, retries, staleness, autoscale


def test_placement_dispatch_race_fails_over_transparently(monkeypatch):
    """A replica chosen while placeable but stopped before the request
    reached its worker: the shutdown envelope (112 with no queue depth)
    fails over to a survivor transparently — the caller never sees it."""
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.REGISTRY.reset()
    r1, r2 = _replica().start(), _replica().start()
    router = serve.Router()
    router.join("r1", server=r1)
    router.join("r2", server=r2)
    first = router.call(op="ls_solve", system="sys", b=RHS[0])
    assert first["ok"]
    pinned = first["trace"]["replica"]
    survivor = "r2" if pinned == "r1" else "r1"
    # the pinned replica dies with NO poll in between: the router still
    # believes it placeable when it places the next request
    (r1 if pinned == "r1" else r2).stop()
    resp = router.call(op="ls_solve", system="sys", b=RHS[1])
    snap = telemetry.snapshot()
    fleet = router.fleet_report()
    router.stop()
    (r1 if survivor == "r1" else r2).stop()
    telemetry.REGISTRY.reset()

    assert resp["ok"] and resp["trace"]["replica"] == survivor
    assert snap["router"]["failovers"] >= 1
    # the corpse was ejected in flight ("shut down in flight")
    assert pinned not in fleet["members"]
    assert snap["router"]["ejects"] >= 1


def test_http_replica_load_report_retries_with_jittered_backoff(
    monkeypatch,
):
    """ONE dropped connection must not read as a dead heartbeat: the
    report fetch walks a 3-attempt jittered exponential ladder before
    surfacing the failure to the ejection logic."""
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.REGISTRY.reset()
    rep = serve.HttpReplica("r", "http://127.0.0.1:1")  # never dialed
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError("connection reset")
        return {"load": {"queue_depth": 0, "worker_alive": True}}

    rep._client.healthz = flaky
    slept: list = []
    rep._sleep = slept.append
    rep._jitter = lambda: 0.5  # pin the jitter draw: delay = b * 2^a
    load = rep.load_report()
    counters = telemetry.REGISTRY.snapshot()["counters"]
    assert load["worker_alive"] and calls["n"] == 3
    assert slept == [pytest.approx(0.05), pytest.approx(0.10)]
    assert counters.get("router.report_retries") == 2

    # a permanently dead peer exhausts the ladder and raises
    dead = serve.HttpReplica("d", "http://127.0.0.1:1")
    dead._client.healthz = flaky  # keeps succeeding -> use a raiser
    def raiser():
        raise OSError("refused")
    dead._client.healthz = raiser
    dead._sleep = slept.append
    dead._jitter = lambda: 0.5
    with pytest.raises(OSError):
        dead.load_report()
    assert len(slept) == 2 + 3  # three more backoffs before giving up
    telemetry.REGISTRY.reset()


def test_poll_stale_but_alive_keeps_placing_then_ejects_on_silence():
    r1 = _replica().start()
    router = serve.Router(serve.RouterParams(heartbeat_timeout_s=5.0))
    router.join("r1", server=r1)
    member = router._members["r1"]

    def hiccup():
        raise OSError("transport hiccup")

    member.replica.load_report = hiccup
    now = time.monotonic()
    # one dropped poll is not a dead replica: still placeable, its last
    # report honestly stamped with its age
    assert router.poll_once(now=now + 1.0) == {"r1": True}
    fleet = router.fleet_report()
    assert fleet["members"]["r1"]["report"]["report_age_s"] >= 0.9
    assert router.call(op="ls_solve", system="sys", b=RHS[0])["ok"]
    # real silence past the timeout: ejected (the 114 ladder)
    assert router.poll_once(now=now + 10.0) == {}
    assert router.fleet_report()["members"] == {}
    router.stop()
    r1.stop()


def test_autoscale_smoke_drill_2_3_2_zero_sheds(monkeypatch):
    """The tier-1 drill: a 2-replica fleet under traffic scales to 3 on
    a tripped p99 target, then drains back to 2 when the pressure is
    declared gone — every caller answer ok, zero sheds, zero 114s, and
    the scale-down is a clean ledgered leave, never an eject."""
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.REGISTRY.reset()
    r1, r2 = _replica().start(), _replica().start()
    router = serve.Router()
    router.join("r1", server=r1)
    router.join("r2", server=r2)
    params = serve.AutoscaleParams(
        min_replicas=2, max_replicas=3, queue_high=1e9, queue_low=1e9,
        p99_high_ms=1e-4, cooldown_ticks=1, idle_ticks=2,
        drain_timeout_s=30.0,
    )
    scaler = serve.Autoscaler(router, lambda name: _replica(), params)

    responses = [
        router.call(op="ls_solve", system="sys", b=b) for b in RHS[:3]
    ]
    d = scaler.step()  # the p99 target trips: 2 -> 3
    assert d["action"] == "scale_up"
    assert len(router.fleet_report()["members"]) == 3
    responses += [
        router.call(op="ls_solve", system="sys", b=b) for b in RHS[3:6]
    ]
    # pressure declared gone: cooldown, idle streak, drain back to 2
    params.p99_high_ms = None
    while len(router.fleet_report()["members"]) > 2 and scaler._tick < 12:
        responses.append(
            router.call(op="ls_solve", system="sys",
                        b=RHS[scaler._tick % len(RHS)])
        )
        scaler.step()
    snap = telemetry.snapshot()
    fleet = router.fleet_report()
    router.stop()
    r1.stop()
    r2.stop()
    telemetry.REGISTRY.reset()

    assert all(r["ok"] for r in responses)  # zero sheds, zero 114s
    assert set(fleet["members"]) == {"r1", "r2"}  # the core survives
    assert snap["autoscale"]["scale_ups"] == 1
    assert snap["autoscale"]["scale_downs"] == 1
    assert snap["autoscale"]["drains_done"] == 1
    assert snap["router"]["leaves"] == 1  # a clean leave ...
    assert snap["router"].get("ejects", 0) == 0  # ... never a 114
    assert snap["serve"].get("shed_admission", 0) == 0
