"""LIBSVM IO round-trip tests (≙ reference ``tests/unit/io_test.py``)."""

import numpy as np

from libskylark_tpu.io import read_libsvm, write_libsvm


def test_roundtrip_dense(tmp_path, rng):
    X = rng.standard_normal((20, 7))
    X[rng.random((20, 7)) < 0.5] = 0.0
    y = rng.integers(0, 3, 20).astype(float)
    write_libsvm(tmp_path / "f", X, y)
    X2, y2 = read_libsvm(tmp_path / "f", n_features=7)
    np.testing.assert_allclose(X2, X, rtol=1e-15)
    np.testing.assert_allclose(y2, y)


def test_roundtrip_sparse(tmp_path, rng):
    X = rng.standard_normal((15, 9))
    X[rng.random((15, 9)) < 0.7] = 0.0
    y = rng.standard_normal(15)
    write_libsvm(tmp_path / "f", X, y)
    Xs, y2 = read_libsvm(tmp_path / "f", n_features=9, sparse=True)
    np.testing.assert_allclose(np.asarray(Xs.todense()), X, rtol=1e-15)
    np.testing.assert_allclose(y2, y, rtol=1e-15)


def test_1_based_indices(tmp_path):
    (tmp_path / "f").write_text("1 1:2.5 3:1.0\n-1 2:0.5\n")
    X, y = read_libsvm(tmp_path / "f")
    assert X.shape == (2, 3)
    np.testing.assert_allclose(X, [[2.5, 0, 1.0], [0, 0.5, 0]])
    np.testing.assert_allclose(y, [1, -1])


def test_pad_features(tmp_path):
    (tmp_path / "f").write_text("0 1:1\n")
    X, _ = read_libsvm(tmp_path / "f", n_features=5)
    assert X.shape == (1, 5)


def test_max_rows(tmp_path):
    (tmp_path / "f").write_text("1 1:1\n2 2:2\n3 3:3\n")
    X, y = read_libsvm(tmp_path / "f", n_features=3, max_rows=2)
    assert X.shape == (2, 3)
    np.testing.assert_allclose(y, [1, 2])
    Xs, ys = read_libsvm(tmp_path / "f", n_features=3, max_rows=2, sparse=True)
    assert Xs.shape == (2, 3)
    np.testing.assert_allclose(np.asarray(Xs.todense()), np.asarray(X))
    # max_rows beyond the file is a no-op
    X3, _ = read_libsvm(tmp_path / "f", max_rows=99)
    assert X3.shape[0] == 3
    # inferred width comes from the KEPT rows only
    X4, _ = read_libsvm(tmp_path / "f", max_rows=2)
    assert X4.shape == (2, 2)
