"""LIBSVM IO round-trip tests (≙ reference ``tests/unit/io_test.py``)."""

import numpy as np
import pytest

from libskylark_tpu.io import read_libsvm, write_libsvm


def test_roundtrip_dense(tmp_path, rng):
    X = rng.standard_normal((20, 7))
    X[rng.random((20, 7)) < 0.5] = 0.0
    y = rng.integers(0, 3, 20).astype(float)
    write_libsvm(tmp_path / "f", X, y)
    X2, y2 = read_libsvm(tmp_path / "f", n_features=7)
    np.testing.assert_allclose(X2, X, rtol=1e-15)
    np.testing.assert_allclose(y2, y)


def test_roundtrip_sparse(tmp_path, rng):
    X = rng.standard_normal((15, 9))
    X[rng.random((15, 9)) < 0.7] = 0.0
    y = rng.standard_normal(15)
    write_libsvm(tmp_path / "f", X, y)
    Xs, y2 = read_libsvm(tmp_path / "f", n_features=9, sparse=True)
    np.testing.assert_allclose(np.asarray(Xs.todense()), X, rtol=1e-15)
    np.testing.assert_allclose(y2, y, rtol=1e-15)


def test_1_based_indices(tmp_path):
    (tmp_path / "f").write_text("1 1:2.5 3:1.0\n-1 2:0.5\n")
    X, y = read_libsvm(tmp_path / "f")
    assert X.shape == (2, 3)
    np.testing.assert_allclose(X, [[2.5, 0, 1.0], [0, 0.5, 0]])
    np.testing.assert_allclose(y, [1, -1])


def test_pad_features(tmp_path):
    (tmp_path / "f").write_text("0 1:1\n")
    X, _ = read_libsvm(tmp_path / "f", n_features=5)
    assert X.shape == (1, 5)


def test_max_rows(tmp_path):
    (tmp_path / "f").write_text("1 1:1\n2 2:2\n3 3:3\n")
    X, y = read_libsvm(tmp_path / "f", n_features=3, max_rows=2)
    assert X.shape == (2, 3)
    np.testing.assert_allclose(y, [1, 2])
    Xs, ys = read_libsvm(tmp_path / "f", n_features=3, max_rows=2, sparse=True)
    assert Xs.shape == (2, 3)
    np.testing.assert_allclose(np.asarray(Xs.todense()), np.asarray(X))
    # max_rows beyond the file is a no-op
    X3, _ = read_libsvm(tmp_path / "f", max_rows=99)
    assert X3.shape[0] == 3
    # inferred width comes from the KEPT rows only
    X4, _ = read_libsvm(tmp_path / "f", max_rows=2)
    assert X4.shape == (2, 2)


# -- byte-source seam (≙ the HDFS reader role, libsvm_io.hpp:1495-1638) ----


def test_memory_source_read(rng):
    from libskylark_tpu.io import MemorySource, read_libsvm

    data = b"1 1:2.0 2:3.0\n-1 2:1.5\n"
    X, y = read_libsvm(MemorySource(data), n_features=2)
    np.testing.assert_allclose(X, [[2.0, 3.0], [0.0, 1.5]])
    np.testing.assert_allclose(y, [1, -1])
    # raw bytes coerce too
    X2, _ = read_libsvm(data, n_features=2)
    np.testing.assert_allclose(X2, X)


def test_stream_from_source(rng):
    from libskylark_tpu.io import MemorySource, stream_libsvm

    lines = [f"{i % 2} 1:{i}.0" for i in range(10)]
    src = MemorySource(("\n".join(lines) + "\n").encode())
    batches = list(stream_libsvm(src, n_features=1, batch=4))
    assert [len(b[1]) for b in batches] == [4, 4, 2]
    got = np.concatenate([np.asarray(b[0])[:, 0] for b in batches])
    np.testing.assert_allclose(got, np.arange(10.0))


def test_stream_no_trailing_newline():
    """The last example must not be lost when the file ends mid-line
    (the EOF chunk carries no terminator — common with hand-edited
    files)."""
    from libskylark_tpu.io import stream_libsvm

    data = b"1 1:1.0\n2 1:2.0\n3 1:3.0"  # note: no final \n
    batches = list(stream_libsvm(data, n_features=1, batch=2))
    assert [len(b[1]) for b in batches] == [2, 1]
    got = np.concatenate([np.asarray(b[1]) for b in batches])
    np.testing.assert_allclose(got, [1, 2, 3])


def test_stream_example_spans_chunk_boundary():
    """chunk_bytes smaller than one line: the carry logic must stitch
    the split line back together, never yielding a half-parsed example."""
    from libskylark_tpu.io import stream_libsvm

    lines = [
        f"{i} 1:{i}.5 2:{i * 10}.25 3:{i * 100}.125" for i in range(7)
    ]
    data = ("\n".join(lines) + "\n").encode()
    assert max(len(l) for l in lines) > 8
    batches = list(
        stream_libsvm(data, n_features=3, batch=3, chunk_bytes=8)
    )
    assert [len(b[1]) for b in batches] == [3, 3, 1]
    X = np.concatenate([np.asarray(b[0]) for b in batches])
    y = np.concatenate([np.asarray(b[1]) for b in batches])
    np.testing.assert_allclose(y, np.arange(7.0))
    np.testing.assert_allclose(X[:, 0], np.arange(7) + 0.5)
    np.testing.assert_allclose(X[:, 2], np.arange(7) * 100 + 0.125)


def test_stream_empty_source():
    """An empty byte stream yields no batches (and no crash) — the
    streaming drivers turn that into their own 'empty stream' errors."""
    from libskylark_tpu.io import MemorySource, stream_libsvm

    assert list(stream_libsvm(b"", n_features=4)) == []
    assert list(stream_libsvm(MemorySource(b""), n_features=4)) == []
    # whitespace/comment-only content parses to zero examples too
    assert list(stream_libsvm(b"\n# nothing\n\n", n_features=4)) == []


def test_stream_raw_bytes_and_memory_source_agree(tmp_path, rng):
    """Raw bytes and an explicit MemorySource take the same path as a
    file: identical batches from all three spellings."""
    from libskylark_tpu.io import MemorySource, stream_libsvm

    X = rng.standard_normal((9, 4))
    X[rng.random((9, 4)) < 0.5] = 0.0
    y = rng.standard_normal(9)
    path = str(tmp_path / "f.libsvm")
    write_libsvm(path, X, y)
    data = open(path, "rb").read()

    def collect(src):
        bs = list(stream_libsvm(src, n_features=4, batch=4))
        return (
            np.concatenate([np.asarray(b[0]) for b in bs]),
            np.concatenate([np.asarray(b[1]) for b in bs]),
        )

    Xf, yf = collect(path)
    Xb, yb = collect(data)
    Xm, ym = collect(MemorySource(data))
    np.testing.assert_array_equal(Xb, Xf)
    np.testing.assert_array_equal(Xm, Xf)
    np.testing.assert_array_equal(yb, yf)
    np.testing.assert_array_equal(ym, yf)
    np.testing.assert_allclose(Xf, X, rtol=1e-15)


def test_scan_libsvm_dims(tmp_path):
    from libskylark_tpu.io import scan_libsvm_dims

    (tmp_path / "f").write_text(
        "# header comment\n1 1:1.0 7:2.0\n\n-1 3:4.0  # trailing\n2 2:1.0"
    )
    assert scan_libsvm_dims(tmp_path / "f") == (3, 7)
    assert scan_libsvm_dims(b"") == (0, 0)
    # tiny chunks: counting must survive lines split across reads
    assert scan_libsvm_dims(b"1 1:1.0\n2 12:3.0\n", chunk_bytes=4) == (2, 12)


def test_file_url_and_scheme_registry(tmp_path):
    from libskylark_tpu.io import (
        MemorySource,
        open_source,
        read_libsvm,
        register_scheme,
    )

    (tmp_path / "f").write_text("1 1:4.0\n")
    X, _ = read_libsvm(f"file://{tmp_path}/f")
    np.testing.assert_allclose(X, [[4.0]])

    register_scheme("testmem", lambda url: MemorySource(b"1 1:7.0\n"))
    X2, _ = read_libsvm("testmem://whatever")
    np.testing.assert_allclose(X2, [[7.0]])
    assert open_source("testmem://x").size() == len(b"1 1:7.0\n")


def test_fsspec_backend_roundtrip():
    """The generic-scheme path goes through fsspec when present (this
    environment bundles it): memory:// is fsspec's built-in store, so this
    exercises the exact code path an hdfs://-style URL takes."""
    pytest.importorskip("fsspec")
    import fsspec

    from libskylark_tpu.io import read_libsvm, stream_libsvm

    with fsspec.open("memory://sky/t.libsvm", "wb") as f:
        f.write(b"1 1:2.0\n0 1:3.0\n")
    X, y = read_libsvm("memory://sky/t.libsvm")
    np.testing.assert_allclose(X, [[2.0], [3.0]])
    np.testing.assert_allclose(y, [1, 0])
    batches = list(stream_libsvm("memory://sky/t.libsvm", n_features=1))
    assert len(batches) == 1 and len(batches[0][1]) == 2


def test_unknown_remote_scheme_raises():
    from libskylark_tpu.io import open_source

    # Without fsspec the ImportError fires at construction; with it, the
    # unknown protocol errors at open() — both inside the raises block.
    with pytest.raises(Exception, match="no-such-proto-xyz|fsspec"):
        open_source("no-such-proto-xyz://bucket/key").open()


class _FlakyFsspec:
    """Stands in for the fsspec module: ``open()`` raises transient
    OSErrors for the first ``fail`` calls, then returns an OpenFile-alike
    whose ``.open()`` yields the payload."""

    def __init__(self, fail: int, payload: bytes = b"payload"):
        self.fail = fail
        self.calls = 0
        self.payload = payload

    def open(self, url, mode):
        self.calls += 1
        if self.calls <= self.fail:
            raise OSError(f"transient failure #{self.calls}")
        payload = self.payload

        class _OpenFile:
            def open(self):
                import io as _io

                return _io.BytesIO(payload)

        return _OpenFile()


def test_fsspec_open_retries_transient_oserror():
    """Satellite: FsspecSource.open() retries transient OSError with
    jittered exponential backoff instead of killing the stream."""
    pytest.importorskip("fsspec")
    from libskylark_tpu.io.source import FsspecSource

    src = FsspecSource("memory://flaky/x", retries=3, backoff=0.1)
    flaky = _FlakyFsspec(fail=2)
    src._fsspec = flaky
    sleeps = []
    src._sleep = sleeps.append
    src._jitter = lambda: 0.5  # deterministic: delay = backoff * 2**k
    with src.open() as f:
        assert f.read() == b"payload"
    assert flaky.calls == 3  # 2 failures + 1 success
    # Exponential steps (jitter pinned): 0.1, 0.2.
    np.testing.assert_allclose(sleeps, [0.1, 0.2])


def test_fsspec_open_retry_budget_exhausted():
    pytest.importorskip("fsspec")
    from libskylark_tpu.io.source import FsspecSource

    src = FsspecSource("memory://flaky/y", retries=2, backoff=0.01)
    flaky = _FlakyFsspec(fail=10)
    src._fsspec = flaky
    src._sleep = lambda s: None
    with pytest.raises(OSError, match="transient failure #3"):
        src.open()
    assert flaky.calls == 3  # first try + 2 retries, then the raise


def test_fsspec_open_retries_counted_in_telemetry(tmp_path, monkeypatch):
    pytest.importorskip("fsspec")
    from libskylark_tpu import telemetry
    from libskylark_tpu.io.source import FsspecSource

    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.reset()
    src = FsspecSource("memory://flaky/z", retries=3, backoff=0.01)
    src._fsspec = _FlakyFsspec(fail=1)
    src._sleep = lambda s: None
    with src.open() as f:
        f.read()
    assert telemetry.REGISTRY.snapshot()["counters"]["io.open_retries"] == 1
    telemetry.reset()


class TestRemoteSchemeIntegration:
    """A REAL non-local fsspec driver (http:// against a live local
    server): the network-remote code path an hdfs:///s3:// URL takes —
    async fsspec filesystem, range/streaming reads over sockets — beyond
    what memory:// exercises (VERDICT round 2 item 8).  ≙ the reference's
    HDFS LIBSVM readers (utility/io/libsvm_io.hpp:1509-1638)."""

    @pytest.fixture()
    def http_root(self, tmp_path):
        pytest.importorskip("fsspec")
        pytest.importorskip("aiohttp")  # fsspec's http driver backend
        import functools
        import threading
        from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer

        handler = functools.partial(
            SimpleHTTPRequestHandler, directory=str(tmp_path)
        )
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        try:
            yield tmp_path, f"http://127.0.0.1:{httpd.server_address[1]}"
        finally:
            httpd.shutdown()
            thread.join(timeout=5)

    def test_stream_libsvm_over_http(self, http_root, rng):
        from libskylark_tpu.io import read_libsvm, stream_libsvm, write_libsvm

        root, base = http_root
        X = rng.standard_normal((37, 5))
        y = (rng.standard_normal(37) > 0).astype(float)
        write_libsvm(root / "data.svm", X, y)
        Xl, yl = read_libsvm(root / "data.svm")

        Xr, yr = read_libsvm(f"{base}/data.svm")
        np.testing.assert_allclose(Xr, Xl)
        np.testing.assert_allclose(yr, yl)

        # Multi-chunk streaming over the socket (chunk_bytes smaller than
        # the file forces several remote reads + carry handling).
        batches = list(
            stream_libsvm(f"{base}/data.svm", 5, batch=10, chunk_bytes=256)
        )
        assert [len(b[1]) for b in batches] == [10, 10, 10, 7]
        np.testing.assert_allclose(np.vstack([b[0] for b in batches]), Xl)

    @pytest.mark.slow
    def test_streaming_predict_over_http(self, http_root, rng, capsys):
        """End-to-end: train locally, then stream predictions straight
        off the remote URL through the skylark-ml CLI."""
        from libskylark_tpu.cli.ml import main
        from libskylark_tpu.io import write_libsvm

        root, base = http_root
        X = rng.standard_normal((48, 4))
        w = rng.standard_normal(4)
        y = np.sign(X @ w)
        write_libsvm(root / "train.svm", X, y)
        write_libsvm(root / "test.svm", X[:20], y[:20])

        assert main([
            "--trainfile", str(root / "train.svm"),
            "--modelfile", str(root / "m.json"),
            "-l", "squared", "-g", "1.0", "-f", "32", "-n", "2", "-i", "10",
        ]) == 0
        capsys.readouterr()
        assert main([
            "--testfile", f"{base}/test.svm",
            "--modelfile", str(root / "m.json"),
            "--outputfile", str(root / "preds.txt"),
            "--batch", "7",
        ]) == 0
        preds = (root / "preds.txt").read_text().splitlines()
        assert len(preds) == 20
