"""Test configuration: force an 8-device virtual CPU mesh and float64.

Multi-chip behavior is tested on virtual CPU devices the way the reference
tests multi-node behavior with `mpirun -np K` on one box
(`tests/unit/CMakeLists.txt:11-38`).  x64 is enabled for numerical-parity
checks against the reference's double-precision semantics.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize force-sets jax_platforms to "axon,cpu"; tests run on
# the virtual 8-device CPU mesh, so override back to cpu-only.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import signal  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Fault-injection tests must never hang the tier-1 run (a botched resume
# path could loop forever waiting on a checkpoint that never appears), so
# every ``faults``-marked test gets a hard per-test alarm.  They stay
# inside the ``-m 'not slow'`` selection on purpose: the recovery paths
# run on every PR.  ``streaming`` tests get the same guard for the same
# reason — a stuck prefetch queue or an unfinished producer thread would
# otherwise block the run forever.
FAULTS_TIMEOUT_S = 120
STREAMING_TIMEOUT_S = 120
GUARD_TIMEOUT_S = 120
TELEMETRY_TIMEOUT_S = 120
# Multi-process elastic streaming runs three real jax.distributed worlds
# back-to-back (reference run, kill-one-rank run, resume run), each with
# its own formation timeout — the alarm must cover the worst-case sum.
DISTRIBUTED_STREAMING_TIMEOUT_S = 900
# Host-level chaos tests (rank death, stragglers, stale-epoch writers,
# repartitioned resumes) simulated in ONE process; real multi-process
# chaos rides the distributed_streaming slow tier instead.
CHAOS_TIMEOUT_S = 120
# Pallas kernel tests run in interpret mode on CPU CI (the compiled
# kernels only exist on TPU); interpret mode executes the kernel body
# as traced jax ops, so a mis-sized grid or a runaway scalar loop
# would otherwise stall the tier-1 run.
KERNELS_TIMEOUT_S = 120
# Adaptive-policy tests run real (small) guarded solves to mature
# profile stores, plus subprocess determinism checks; a wedged store
# merge or a hung subprocess must not stall the tier-1 run.
POLICY_TIMEOUT_S = 120
# Serve-layer tests run a real worker thread behind a blocking queue
# (plus an HTTP loopback); a worker that never drains, a future that
# never resolves, or a leaked socket must not stall the tier-1 run.
SERVE_TIMEOUT_S = 120
# Overlap tests run full streaming passes twice (overlapped vs serial)
# plus kill-resume rounds under donation; a fold that never syncs or a
# resume that re-opens a wedged source must not stall the tier-1 run.
OVERLAP_TIMEOUT_S = 120
# Trace-plane tests drive live servers (worker thread + HTTP scrapers)
# and fleet folds; a scrape that deadlocks against the worker must not
# stall the tier-1 run.
TRACE_TIMEOUT_S = 120
# Fleet tests run multi-worker servers, a router front door with
# heartbeat polling, and sharded-dispatch parity probes over virtual
# devices; a placement that never resolves or a worker pinned to a
# wedged device must not stall the tier-1 run.
FLEET_TIMEOUT_S = 120
# Refine tests drive host-gated refinement sweeps (certified gates,
# stagnation/ladder fallback, policy earning); a sweep loop that never
# meets its gate must not stall the tier-1 run.
REFINE_TIMEOUT_S = 120
# Graph tests fold streamed edge blocks through elastic runs and drive
# served PPR/embed queries behind the worker thread; a wedged fold or
# an unresolved future must not stall the tier-1 run.
GRAPH_TIMEOUT_S = 120
# Distributed-training tests stream feature blocks through elastic
# folds, run multi-chunk ADMM under the resilient runner (including
# kill/resume rounds), and simulate consensus merges across ranks in
# one process; a wedged stream or a resume that waits on a checkpoint
# that never lands must not stall the tier-1 run.
TRAIN_TIMEOUT_S = 180
# QoS tests drive weighted-fair tenant lanes and token-bucket quotas
# through live servers under concurrent multi-tenant load; a lane the
# scheduler never visits or a future that never resolves must not
# stall the tier-1 run.
QOS_TIMEOUT_S = 120
# Result-cache tests drive the front-door cache across live-registry
# epoch bumps behind the worker thread; a wedged invalidation or an
# unresolved future must not stall the tier-1 run.
CACHE_TIMEOUT_S = 120
# Durability tests journal real registries through fsync'd appends,
# SIGKILL child replicas mid-update-stream, and replay recovery; a
# child that never dies or a recover that waits on a journal handle
# must not stall the tier-1 run.
DURABILITY_TIMEOUT_S = 120

_TIMEOUT_MARKS = {
    "faults": FAULTS_TIMEOUT_S,
    "streaming": STREAMING_TIMEOUT_S,
    "guard": GUARD_TIMEOUT_S,
    "telemetry": TELEMETRY_TIMEOUT_S,
    "distributed_streaming": DISTRIBUTED_STREAMING_TIMEOUT_S,
    "chaos": CHAOS_TIMEOUT_S,
    "kernels": KERNELS_TIMEOUT_S,
    "policy": POLICY_TIMEOUT_S,
    "serve": SERVE_TIMEOUT_S,
    "overlap": OVERLAP_TIMEOUT_S,
    "trace": TRACE_TIMEOUT_S,
    "fleet": FLEET_TIMEOUT_S,
    "refine": REFINE_TIMEOUT_S,
    "graph": GRAPH_TIMEOUT_S,
    "train": TRAIN_TIMEOUT_S,
    "qos": QOS_TIMEOUT_S,
    "cache": CACHE_TIMEOUT_S,
    "durability": DURABILITY_TIMEOUT_S,
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "faults: fault-injection / resilience tests (preemption, corrupt "
        "checkpoints, transient IO); tier-1, guarded by a per-test "
        f"{FAULTS_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "streaming: out-of-core streaming engine tests (partial sketches, "
        "prefetch pipeline, resumable passes) on small synthetic data; "
        f"tier-1, guarded by a per-test {STREAMING_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "perf: performance/latency assertions (wall-clock thresholds, "
        "machine-sensitive); NOT tier-1 — auto-skipped unless "
        "SKYLARK_RUN_PERF=1",
    )
    config.addinivalue_line(
        "markers",
        "guard: numerical-health guard tests (sentinels, certification, "
        "recovery ladder, fault-injected recovery); tier-1, guarded by a "
        f"per-test {GUARD_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "telemetry: observability-layer tests (spans, metrics registry, "
        "JSONL run ledger, run_summary contract); tier-1, guarded by a "
        f"per-test {TELEMETRY_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "distributed_streaming: multi-process elastic streaming tests "
        "(kill-one-rank resume over real jax.distributed worlds); slow "
        f"tier, guarded by a per-test {DISTRIBUTED_STREAMING_TIMEOUT_S}s "
        "timeout",
    )
    config.addinivalue_line(
        "markers",
        "chaos: host-level chaos tests (rank death, stragglers, stale-"
        "epoch fencing, repartition-on-resume) simulated in one process; "
        f"tier-1, guarded by a per-test {CHAOS_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "kernels: Pallas kernel tests (window/flat scatter, fused "
        "stream chunks) in interpret mode on CPU CI; tier-1, guarded "
        f"by a per-test {KERNELS_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "policy: adaptive execution-policy tests (profile store, routing "
        "decisions, warm start, bit-parity contract); tier-1, guarded by "
        f"a per-test {POLICY_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "serve: sketch-serving layer tests (cross-request coalescing, "
        "bitwise request isolation, admission/deadline shedding, "
        "transports); tier-1, guarded by a per-test "
        f"{SERVE_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "overlap: async device-overlap streaming tests (overlapped vs "
        "serial bitwise parity, kill-resume under donation, sync-point "
        "discipline); tier-1, guarded by a per-test "
        f"{OVERLAP_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "trace: fleet observability-plane tests (request tracing, flight "
        "recorder, cross-host aggregation, exposition endpoints); tier-1, "
        f"guarded by a per-test {TRACE_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "fleet: fleet-scale serving tests (device-parallel dispatch "
        "parity, replicated workers, router placement / membership / "
        "failover); tier-1, guarded by a per-test "
        f"{FLEET_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "refine: certified mixed-precision refinement tests (route-OFF "
        "bitwise parity, certified convergence, stagnation/ladder "
        "fallback, served cond-est, quasirandom sketch interchange); "
        f"tier-1, guarded by a per-test {REFINE_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "graph: graph-analytics tests (streamed edge-list folds, chained "
        "sharded sketches, streaming ASE, served PPR/embed queries); "
        f"tier-1, guarded by a per-test {GRAPH_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "train: distributed kernel-machine training tests (world=1 "
        "bitwise parity, simulated-rank consensus, kill/resume through "
        "the ADMM loop, guard recovery mid-stream); tier-1, guarded by "
        f"a per-test {TRAIN_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "qos: multi-tenant QoS tests (deficit-weighted tenant lanes, "
        "token-bucket quota sheds, tenant-stamped envelopes/counters); "
        f"tier-1, guarded by a per-test {QOS_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "cache: front-door result-cache tests (bitwise hit parity, "
        "epoch-bump invalidation, LRU/byte bounds, fleet hit sharing); "
        f"tier-1, guarded by a per-test {CACHE_TIMEOUT_S}s timeout",
    )
    config.addinivalue_line(
        "markers",
        "durability: serve durability tests (write-ahead journal, "
        "bitwise crash recovery, SIGKILL chaos drills, exactly-once "
        "idempotency across failover); tier-1, guarded by a per-test "
        f"{DURABILITY_TIMEOUT_S}s timeout",
    )


def pytest_collection_modifyitems(config, items):
    # perf tests assert wall-clock behavior that flakes on loaded CI
    # hosts; tier-1 selects with -m 'not slow', which would include
    # them, so they gate on an explicit env opt-in instead.
    if os.environ.get("SKYLARK_RUN_PERF") == "1":
        return
    skip = pytest.mark.skip(
        reason="perf test: machine-sensitive timing; set SKYLARK_RUN_PERF=1"
    )
    for item in items:
        if item.get_closest_marker("perf") is not None:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _marked_timeout(request):
    limits = [
        (name, seconds)
        for name, seconds in _TIMEOUT_MARKS.items()
        if request.node.get_closest_marker(name) is not None
    ]
    if not limits:
        yield
        return
    name, seconds = min(limits, key=lambda kv: kv[1])

    def _alarm(signum, frame):
        raise TimeoutError(
            f"{name} test exceeded {seconds}s hard timeout"
        )

    old = signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture
def rng():
    return np.random.default_rng(0)
