"""Test configuration: force an 8-device virtual CPU mesh and float64.

Multi-chip behavior is tested on virtual CPU devices the way the reference
tests multi-node behavior with `mpirun -np K` on one box
(`tests/unit/CMakeLists.txt:11-38`).  x64 is enabled for numerical-parity
checks against the reference's double-precision semantics.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize force-sets jax_platforms to "axon,cpu"; tests run on
# the virtual 8-device CPU mesh, so override back to cpu-only.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
