"""Sharded end-to-end algorithm coverage on the 8-device virtual mesh.

Beyond the sketch-level sharding tests: whole algorithms (Blendenpik, KRR,
ADMM) run with sharded inputs and match (or train as well as) their local
runs — the framework-level analogue of the reference's `mpirun -np K`
integration tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import SketchContext
from libskylark_tpu.linalg import faster_least_squares
from libskylark_tpu.ml import (
    ADMMParams,
    BlockADMMSolver,
    GaussianKernel,
    approximate_kernel_ridge,
)
from libskylark_tpu.parallel import ROWS, COLS, default_mesh, make_mesh, shard, shard_rows


@pytest.fixture(scope="module")
def mesh():
    return default_mesh()  # (4, 2) on the 8 virtual devices


@pytest.mark.slow
class TestShardedAlgorithms:
    def test_blendenpik_sharded_matches_local(self, rng, mesh):
        A = jnp.asarray(rng.standard_normal((2048, 24)))
        b = jnp.asarray(rng.standard_normal(2048))
        x_local, _ = faster_least_squares(A, b, SketchContext(seed=1))
        As = shard_rows(A, mesh)
        bs = shard_rows(b, mesh)
        x_shard, _ = faster_least_squares(As, bs, SketchContext(seed=1))
        np.testing.assert_allclose(
            np.asarray(x_shard), np.asarray(x_local), rtol=1e-7, atol=1e-9
        )

    def test_krr_sharded_matches_local(self, rng, mesh):
        X = jnp.asarray(rng.standard_normal((512, 8)))
        y = jnp.asarray(np.sin(np.asarray(X).sum(1)))
        k = GaussianKernel(8, 2.0)
        m_local = approximate_kernel_ridge(
            k, X, y, 0.05, 256, SketchContext(seed=2)
        )
        m_shard = approximate_kernel_ridge(
            k, shard_rows(X, mesh), shard_rows(y, mesh), 0.05, 256,
            SketchContext(seed=2),
        )
        np.testing.assert_allclose(
            np.asarray(m_shard.W), np.asarray(m_local.W), rtol=1e-6, atol=1e-8
        )

    def test_admm_with_sharded_partitions(self, rng, mesh):
        n, d = 256, 6
        X = np.vstack([
            rng.standard_normal((n // 2, d)) - 1.5,
            rng.standard_normal((n // 2, d)) + 1.5,
        ])
        y = np.array([0] * (n // 2) + [1] * (n // 2))
        perm = rng.permutation(n)
        X, y = X[perm], y[perm]
        k = GaussianKernel(d, 2.0)
        ctx = SketchContext(seed=3)
        maps = [k.create_rft(64, "regular", ctx) for _ in range(2)]
        solver = BlockADMMSolver(
            "hinge", "l2", maps,
            ADMMParams(maxiter=20, lam=0.005, data_partitions=8),
        )
        Xs = shard(jnp.asarray(X), mesh, (ROWS, COLS))
        m = solver.train(Xs, y)
        pred = np.asarray(m.predict_labels(jnp.asarray(X), m.classes))
        assert (pred == y).mean() > 0.9

    def test_streaming_svd_sharded_panels(self, rng, mesh):
        from libskylark_tpu.linalg import (
            SVDParams,
            streaming_approximate_svd,
            synthetic_lowrank_blocks,
        )

        ctx = SketchContext(seed=41)
        m, n, r = 4096, 64, 5
        bf = synthetic_lowrank_blocks(ctx, m, n, r, noise=0.01)
        ctx2 = SketchContext(seed=41)
        bf2 = synthetic_lowrank_blocks(ctx2, m, n, r, noise=0.01)
        # sharded panels must produce the same factorization as unsharded
        _, s1, V1 = streaming_approximate_svd(
            bf, (m, n), r, ctx, SVDParams(num_iterations=1), block_rows=1024
        )
        _, s2, V2 = streaming_approximate_svd(
            bf2, (m, n), r, ctx2, SVDParams(num_iterations=1),
            block_rows=1024, mesh=mesh,
        )
        # f32 panels: sharded psum accumulation order differs — same
        # factorization up to f32 roundoff (reference oracle tolerance).
        np.testing.assert_allclose(
            np.asarray(s1), np.asarray(s2), rtol=1e-4
        )
        np.testing.assert_allclose(
            np.abs(np.asarray(V1.T @ V2)), np.eye(r), atol=1e-3
        )

    def test_1d_mesh_also_works(self, rng):
        mesh1 = make_mesh((8,), (ROWS,))
        A = jnp.asarray(rng.standard_normal((512, 16)))
        b = jnp.asarray(rng.standard_normal(512))
        As = shard(A, mesh1, ROWS)
        x, _ = faster_least_squares(As, b, SketchContext(seed=4))
        x_ref = np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]
        np.testing.assert_allclose(np.asarray(x), x_ref, rtol=1e-6, atol=1e-8)
