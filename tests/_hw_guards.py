"""Hardware numerics guards, all run in ONE process / ONE backend init.

Executed as a child by ``test_pallas_hw.py`` (which strips the suite's
CPU pin so jax picks its default backend).  Each guard prints exactly one
line ``GUARD <name> OK|FAIL <detail>``; a non-TPU backend prints
``SKIP-NOT-TPU <backend>`` and exits.  Runnable standalone on a bench
chip: ``python tests/_hw_guards.py``.

Round-4 consolidation (VERDICT r3 weak #3): the previous suite paid a
full backend init through the axon tunnel per guard (8 subprocesses ×
420 s worst case ≈ 56 min, and a congested tunnel read as 8 FAILURES).
One init amortizes the tunnel cost across all guards (now 10) and the parent maps
a child timeout to skip-with-reason, not failure.
"""

from __future__ import annotations

import os
import traceback
from contextlib import contextmanager


@contextmanager
def _env_restored():
    """Guards toggle SKYLARK_* gates; running in one process means those
    mutations would leak into later guards — snapshot and restore."""
    saved = {
        k: os.environ.get(k)
        for k in (
            "SKYLARK_NO_FRFT_GEMM",
            "SKYLARK_NO_PALLAS",
            "SKYLARK_NO_SRHT_GEMM",
            "SKYLARK_NO_PPT_DFT",
        )
    }
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def guard_rfut_rowwise_compiled():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu.sketch import pallas_fut
    from libskylark_tpu.sketch.fut import wht

    rng = np.random.default_rng(0)
    m, n, nb = 256, 512, 512
    x = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    d = jnp.asarray(np.sign(rng.standard_normal(n)), jnp.float32)
    out = pallas_fut.rfut_rowwise(x, d, nb, interpret=False)  # compiled
    ref = wht(x * d[None, :], axis=1)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
    )


def guard_bf16_split_accuracy():
    """An astype-based split (``x - bf16(x)``) collapses to single-bf16
    accuracy on TPU (XLA elides the f32→bf16→f32 convert pair); the
    bit-mask split must hold ~f32 accuracy on hardware."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu.core.context import SketchContext
    from libskylark_tpu.sketch.fjlt import FJLT
    from libskylark_tpu.sketch.hash import CWT

    rng = np.random.default_rng(0)
    n, s, m = 1024, 256, 512
    A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    S = FJLT(n, s, SketchContext(seed=3))
    assert S._gemm_wins(jnp.float32)
    out = np.asarray(
        jax.jit(lambda A: S._apply_srht_gemm(A, rowwise=True))(A), np.float64
    )
    G = np.asarray(S._srht_matrix(jnp.float32), np.float64)
    ref = (np.asarray(A, np.float64) @ G) / np.sqrt(s)
    rel = np.abs(out - ref).max() / np.abs(ref).max()
    assert rel < 2e-5, f"FJLT split degraded on hardware: {rel}"
    Sc = CWT(m, 64, SketchContext(seed=5))
    outc = np.asarray(
        jax.jit(lambda A: Sc.apply(A, "columnwise"))(A), np.float64
    )
    M = np.asarray(Sc._hash_matrix(jnp.float32), np.float64)
    refc = M.T @ np.asarray(A, np.float64)
    relc = np.abs(outc - refc).max() / np.abs(refc).max()
    assert relc < 2e-5, f"CWT split degraded on hardware: {relc}"


def guard_wht_f32_accuracy():
    """Guards the MXU default-precision hazard in the WHT chain."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu.sketch.fut import _hadamard, wht

    rng = np.random.default_rng(2)
    m, n = 256, 4096
    x = rng.standard_normal((m, n)).astype(np.float32)
    got = np.asarray(
        jax.jit(lambda x: wht(x, axis=1))(jnp.asarray(x)), np.float64
    )
    H = np.asarray(_hadamard(12), np.float64)
    ref = (x.astype(np.float64) @ H.T) / np.sqrt(n)
    rel = np.abs(got - ref).max() / np.abs(ref).max()
    assert rel < 2e-5, f"wht f32 degraded on hardware: {rel}"


def guard_psd_gram_precision():
    """`ml/krr.py::_psd_gram` must keep its precision='highest' pin."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu.ml.krr import _psd_gram

    rng = np.random.default_rng(3)
    m, s = 4096, 256
    Z = jnp.asarray(rng.standard_normal((m, s)), jnp.float32)
    lam = jnp.float32(1e-4)
    G = np.asarray(
        jax.jit(lambda Z: _psd_gram(Z.T, Z) + lam * jnp.eye(s))(Z), np.float64
    )
    ref = (
        np.asarray(Z, np.float64).T @ np.asarray(Z, np.float64)
        + 1e-4 * np.eye(s)
    )
    rel = np.abs(G - ref).max() / np.abs(ref).max()
    assert rel < 2e-5, f"_psd_gram degraded on hardware: {rel}"
    L = np.linalg.cholesky(G)  # PSD property survives
    assert np.isfinite(L).all()


def guard_streaming_svd_orthogonality():
    """U orthonormal to ~1e-3 in f32; an un-pinned Gram sends it ~1e-2."""
    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu import SketchContext
    from libskylark_tpu.linalg import (
        SVDParams,
        streaming_approximate_svd,
        synthetic_lowrank_blocks,
    )

    m, n, k, br = 100_000, 256, 20, 25_000
    blocks = synthetic_lowrank_blocks(
        SketchContext(seed=5), m, n, k, noise=0.01, dtype=jnp.float32
    )
    U, s, V = streaming_approximate_svd(
        blocks, (m, n), k, SketchContext(seed=6),
        SVDParams(num_iterations=1), block_rows=br, materialize_u=True,
    )
    G = np.asarray(jnp.dot(U.T, U, precision="highest"), np.float64)
    err = np.abs(G - np.eye(k)).max()
    assert err < 1.5e-3, f"streaming-SVD U lost orthogonality: {err}"


def guard_frft_realized_split():
    """Fastfood realized-W f32 4-pass split vs the streaming form."""
    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu import SketchContext
    from libskylark_tpu.sketch import FastGaussianRFT

    rng = np.random.default_rng(4)
    n, s, m = 512, 1024, 4096
    A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    S = FastGaussianRFT(n, s, SketchContext(seed=7), sigma=2.0)
    assert S._realize_wins(jnp.float32, m)
    with _env_restored():
        fast = np.asarray(S.apply(A, "rowwise"))
        os.environ["SKYLARK_NO_FRFT_GEMM"] = "1"
        ref = np.asarray(S.apply(A, "rowwise"))
    err = np.abs(fast - ref).max()
    assert err < 5e-4, f"FRFT realized split degraded on hardware: {err}"


def guard_mmt_scaled_onehot_split():
    """MMT scaled-one-hot f32 path vs the f64 host oracle."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu import SketchContext
    from libskylark_tpu.sketch import MMT

    rng = np.random.default_rng(5)
    n, s, m = 1024, 128, 512
    A = jnp.asarray(rng.standard_normal((n, m)), jnp.float32)
    S = MMT(n, s, SketchContext(seed=9))
    out_d = np.asarray(
        jax.jit(lambda A: S.apply(A, "columnwise"))(A), np.float64
    )
    M = np.asarray(S._hash_matrix(jnp.float32), np.float64)
    ref = M.T @ np.asarray(A, np.float64)
    rel = np.abs(out_d - ref).max() / np.abs(ref).max()
    assert rel < 5e-5, f"MMT scaled split degraded on hardware: {rel}"


def guard_fjlt_pallas_branch_compiled():
    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu import SketchContext
    from libskylark_tpu.sketch import FJLT

    rng = np.random.default_rng(1)
    n, s, m = 512, 64, 256
    A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
    S1 = FJLT(n, s, SketchContext(seed=3))
    with _env_restored():
        out = S1.apply(A, "rowwise")  # gate picks a TPU path
        os.environ["SKYLARK_NO_PALLAS"] = "1"
        os.environ["SKYLARK_NO_SRHT_GEMM"] = "1"
        ref = S1.apply(A, "rowwise")  # forced XLA path, same transform
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


def guard_pallas_scatter_compiled():
    """The two-pass segment-sum kernel must compile (Mosaic) and match
    the XLA scatter on hardware — interpret-mode CPU parity cannot see
    Mosaic lowering breakage (dynamic scalar stores, sublane cumsum)."""
    from libskylark_tpu.sketch.pallas_scatter import self_check, supported

    nnz, T = 40_000, 1 << 17
    assert supported(nnz, T)
    err = self_check(nnz, T)
    assert err < 1e-5, f"pallas scatter diverged on hardware: {err}"


def guard_pallas_window_compiled():
    """The windowed row scatter-add kernel must compile (Mosaic) and
    match segment_sum on hardware — its scalar-indexed VECTOR
    read-modify-write on the VMEM scratch is exactly the construct
    Mosaic may refuse on some TPU generations, and interpret-mode CPU
    parity cannot see that.  Also pins the fused-chunk contract on
    hardware: the acc-folded emit must be BITWISE equal to kernel +
    separate add (one IEEE add either way)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu.sketch.pallas_window import (
        scatter_rows,
        self_check,
        supported,
    )

    k, s, m = 65_536, 1024, 256
    assert supported(k, s, m)
    err = self_check(k, s, m)
    assert err < 1e-5, f"pallas window kernel diverged on hardware: {err}"
    kb, kv, ka, kacc = jax.random.split(jax.random.PRNGKey(17), 4)
    b = jax.random.randint(kb, (k,), 0, s, jnp.int32)
    v = jax.random.normal(kv, (k,), jnp.float32)
    A = jax.random.normal(ka, (k, m), jnp.float32)
    acc = jax.random.normal(kacc, (s, m), jnp.float32)
    fused = scatter_rows(A, b, v, s, acc=acc)
    unfused = acc + scatter_rows(A, b, v, s)
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(unfused))


def guard_fjlt_sampled_compiled():
    """The fused sampled-FJLT kernel (round 5: selection + rescale in
    the epilogue) must either pass its compiled probe AND match the
    two-step path on hardware, or report cleanly that Mosaic refuses
    the lane gather (the production gate then keeps the two-step path —
    a refusal is a finding, not a failure)."""
    import warnings

    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu.sketch import fjlt as fjlt_mod
    from libskylark_tpu.sketch import pallas_fut

    m, nb, s = 256, 4096, 1024
    tm = pallas_fut._tile_rows(m, nb)
    assert pallas_fut.supported_sampled(m, nb, nb, s), "gate must admit"
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        ok = fjlt_mod._sampled_kernel_compiles(jnp.float32, nb, s, tm)
    if not ok:
        msgs = "; ".join(str(w.message)[:160] for w in caught)
        # A kernel that LOWERS but miscomputes is a hardware failure,
        # not a clean Mosaic refusal — the probe's warning text
        # distinguishes the two.
        assert "miscomputed" not in msgs, (
            f"fused sampled-FJLT compiled but miscomputed: {msgs}"
        )
        print(f"  fused kernel unavailable on this backend: {msgs}")
        return  # clean refusal — two-step fallback is the contract
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((m, nb)).astype(np.float32))
    d = jnp.asarray(rng.choice([-1.0, 1.0], nb).astype(np.float32))
    idx = rng.integers(0, nb, s).astype(np.int32)
    out = np.asarray(pallas_fut.rfut_rowwise_sampled(x, d, nb, idx))
    base = np.asarray(pallas_fut.rfut_rowwise(x, d, nb))
    ref = base[:, idx] * np.sqrt(nb / s)
    err = np.abs(out - ref).max() / max(np.abs(ref).max(), 1e-30)
    assert err < 1e-5, f"fused sampled-FJLT diverged on hardware: {err}"


GUARDS = [
    ("rfut_rowwise_compiled", guard_rfut_rowwise_compiled),
    ("pallas_scatter_compiled", guard_pallas_scatter_compiled),
    ("pallas_window_compiled", guard_pallas_window_compiled),
    ("fjlt_sampled_compiled", guard_fjlt_sampled_compiled),
    ("bf16_split_accuracy", guard_bf16_split_accuracy),
    ("wht_f32_accuracy", guard_wht_f32_accuracy),
    ("psd_gram_precision", guard_psd_gram_precision),
    ("streaming_svd_orthogonality", guard_streaming_svd_orthogonality),
    ("frft_realized_split", guard_frft_realized_split),
    ("mmt_scaled_onehot_split", guard_mmt_scaled_onehot_split),
    ("fjlt_pallas_branch_compiled", guard_fjlt_pallas_branch_compiled),
]


def main() -> int:
    import jax

    # The axon sitecustomize overrides JAX_PLATFORMS; restore env
    # semantics so a deliberate CPU run skips instead of touching the
    # tunnel (the parent test strips JAX_PLATFORMS from the child env,
    # so real guard runs still get the default backend).
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if jax.default_backend() != "tpu":
        print(f"SKIP-NOT-TPU {jax.default_backend()}", flush=True)
        return 0
    failed = 0
    for name, fn in GUARDS:
        try:
            fn()
            print(f"GUARD {name} OK", flush=True)
        except Exception as e:  # noqa: BLE001 — every guard must report
            failed += 1
            detail = f"{type(e).__name__}: {e}".replace("\n", " | ")[:500]
            print(f"GUARD {name} FAIL {detail}", flush=True)
            traceback.print_exc()
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
