"""Child process for the serve-durability SIGKILL chaos drills.

Usage::

    python tests/_journal_child.py <state_dir> <mode> <updates>

Runs ONE serving replica with a journaled registry (``ServeParams.
state_dir``) and drives a deterministic update stream through the real
request path (``op:"update"`` with idempotency keys, admission queue,
batcher worker).  ``mode``:

- ``control``: apply ``<updates>`` updates, stop cleanly, write the
  registry digest to ``<state_dir>/digest.json`` and print
  ``JOURNAL-OK``.  The never-crashed reference.
- ``die-after``: apply the FULL stream, but a
  :class:`JournalFaultPlan` SIGKILLs the process inside the commit
  window of update ``<updates> - 1`` — journal append durable, publish
  never happens.  A real uncatchable death (returncode -9); recovery
  must REPLAY that journaled record, landing at the same epoch as a
  ``control`` run of ``<updates>`` updates.
- ``torn``: same, but the fault tears the frame mid-write (half the
  bytes, fsync'd) before killing.  The record was never durable;
  recovery must truncate it and land at ``<updates> - 1`` updates.

The update stream and registered entities are seeded, so the parent
compares the RECOVERED registry's digest (computed with :func:`digest`
imported from this module) bitwise against the control child's.
"""

from __future__ import annotations

import json
import os
import sys
import zlib


# Registrations journal too: system = append 0, graph = append 1, so
# update k is journal append index REG_APPENDS + k.
REG_APPENDS = 2


def digest(registry) -> dict:
    """Bitwise identity of a registry: epoch counter, the full epoch
    ledger, a CRC over every entity's exact bytes, and the idempotency
    window.  Two registries with equal digests serve the same bits."""
    import numpy as np

    crc = 0

    def fold(*arrays):
        nonlocal crc
        for a in arrays:
            crc = zlib.crc32(np.ascontiguousarray(a).tobytes(), crc)

    for name in sorted(registry.systems):
        s = registry.get_system(name)
        fold(s.A[: s.m], s.SA, s.Qt, s.R)
        crc = zlib.crc32(repr(sorted(s.retired)).encode(), crc)
    for name in sorted(registry.graphs):
        g = registry.get_graph(name)
        fold(g.X, g.G.indptr, g.G.indices)
        crc = zlib.crc32(repr(list(g.G.vertices)).encode(), crc)
    for name in sorted(registry.models):
        m = registry.get_model(name)
        for attr in ("X_train", "A", "W"):
            a = getattr(m, attr, None)
            if a is not None:
                fold(np.asarray(a))
    # Lists, not tuples: the control digest round-trips through JSON.
    idem = sorted(
        [t, k, rec["epoch"]]
        for (t, k), rec in registry._idem.items()
    )
    return {
        "epoch": registry.epoch,
        "epoch_log": registry.epoch_log,
        "crc": crc,
        "idem": idem,
    }


N_V = 16  # graph vertex universe (live folds stay over registered ids)


def build_stream(n: int):
    """The deterministic update-request stream: cycles row appends,
    graph folds (chords over the registered ring vertices — live folds
    reject vertex growth), and row downdates of distinct indices, every
    request carrying a derived idempotency key."""
    import numpy as np

    rng = np.random.default_rng(7)
    reqs = []
    for i in range(n):
        if i % 3 == 0:
            reqs.append({
                "op": "update", "system": "sys", "idem_key": f"upd-{i}",
                "append": rng.normal(size=(2, 5)).tolist(),
            })
        elif i % 3 == 1:
            u = i % N_V
            reqs.append({
                "op": "update", "graph": "g", "idem_key": f"upd-{i}",
                "edges": [[u, (u + 5 + i % 7) % N_V]],
            })
        else:
            reqs.append({
                "op": "update", "system": "sys", "idem_key": f"upd-{i}",
                "drop": [i],
            })
    return reqs


def make_server(state_dir: str, plan=None):
    import numpy as np

    from libskylark_tpu import serve
    from libskylark_tpu.serve.journal import Journal
    from libskylark_tpu.serve.registry import Registry

    params = serve.ServeParams(warm_start=False, prime=False)
    srv = serve.Server(params, seed=11)
    # Journal with the fault plan threaded in (ServeParams has no fault
    # seam on purpose — chaos is a test-only concern).
    srv.registry = Registry(
        cache=srv.cache,
        journal=Journal(state_dir, compact_every=0, faults=plan),
    )
    rng = np.random.default_rng(3)
    srv.register_system(
        "sys", rng.normal(size=(24, 5)), sketch_type="CWT", capacity=96
    )
    from libskylark_tpu.graph.graph import SimpleGraph

    ring = [(v, (v + 1) % N_V) for v in range(N_V)]
    srv.register_graph("g", SimpleGraph(ring), k=2)
    return srv


def main() -> int:
    state_dir, mode, updates = sys.argv[1], sys.argv[2], int(sys.argv[3])
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from libskylark_tpu.resilient.faults import JournalFaultPlan

    plan = None
    if mode == "die-after":
        plan = JournalFaultPlan(
            die_after_journal_before_publish=REG_APPENDS + updates - 1
        )
    elif mode == "torn":
        plan = JournalFaultPlan(torn_journal_at=REG_APPENDS + updates - 1)

    srv = make_server(state_dir, plan).start()
    # Crash modes run the whole stream — the fault kills mid-stream.
    n = updates if mode == "control" else updates + 2
    for req in build_stream(n):
        resp = srv.call(req)
        if not resp.get("ok"):
            print(f"JOURNAL-ERR {resp['error']}", flush=True)
            return 2
    srv.stop()
    if mode != "control":  # the fault should have killed us above
        print("JOURNAL-SURVIVED", flush=True)
        return 3
    with open(os.path.join(state_dir, "digest.json"), "w",
              encoding="utf-8") as fh:
        json.dump(digest(srv.registry), fh)
    print("JOURNAL-OK", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
