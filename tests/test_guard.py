"""Numerical-health guard layer: sentinels, certification, the recovery
ladder, and fault-injected end-to-end recovery (ISSUE PR 4 acceptance).

All tests run under the ``guard`` marker (tier-1, 120 s per-test alarm).
x64 is on (conftest), so f64 is the default dtype throughout.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import guard
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.linalg.least_squares import (
    approximate_least_squares,
    exact_least_squares,
    streaming_least_squares,
)
from libskylark_tpu.resilient import FaultPlan
from libskylark_tpu.utils.exceptions import NumericalHealthError

pytestmark = pytest.mark.guard


def _ls_problem(rng, m=240, n=8, noise=1e-3):
    """Tall LS problem with a planted solution, so recovered solutions
    are comparable through their residuals."""
    A = rng.normal(size=(m, n))
    x_true = rng.normal(size=n)
    b = A @ x_true + noise * rng.normal(size=m)
    return jnp.asarray(A), jnp.asarray(b)


def _residual(A, x, b):
    return float(jnp.linalg.norm(A @ x - b))


# ---------------------------------------------------------------------------
# sentinels


def test_finite_probe_trees(rng):
    clean = {"a": jnp.ones((3, 2)), "n": jnp.arange(3)}
    assert guard.tree_all_finite(clean)
    poisoned = {"a": jnp.ones((3, 2)).at[1, 1].set(jnp.nan), "n": jnp.arange(3)}
    assert not guard.tree_all_finite(poisoned)
    # int-only trees are vacuously finite
    assert guard.tree_all_finite({"n": jnp.arange(3)})


def test_check_finite_raises_with_stage(rng):
    with pytest.raises(NumericalHealthError) as ei:
        guard.check_finite(jnp.asarray([1.0, jnp.inf]), "my_stage")
    assert ei.value.stage == "my_stage"
    assert ei.value.code == 108


def test_finite_probe_is_jittable(rng):
    f = jax.jit(lambda t: guard.finite_probe(t))
    assert bool(f({"x": jnp.ones(4)}))
    assert not bool(f({"x": jnp.asarray([1.0, jnp.nan])}))


def test_guarded_entrypoints_work_under_enclosing_jit(rng):
    """A caller may jit a whole pipeline around the guarded solvers (the
    multichip dry run does exactly this); the host-side ladder cannot run
    mid-trace, so the entrypoints must emit their plain unguarded graph
    instead of raising ConcretizationTypeError."""
    from libskylark_tpu.linalg.svd import approximate_svd

    A, b = _ls_problem(rng, m=120, n=6)
    assert not guard.is_traced(A, b)

    @jax.jit
    def step(A, b):
        U, s, V = approximate_svd(A, 3, SketchContext(seed=7))
        x = approximate_least_squares(A, b, SketchContext(seed=8))
        return s, x

    s, x = step(A, b)
    assert np.isfinite(np.asarray(s)).all()
    assert np.isfinite(np.asarray(x)).all()
    x_eager = approximate_least_squares(A, b, SketchContext(seed=8))
    np.testing.assert_allclose(
        np.asarray(x), np.asarray(x_eager), rtol=1e-9, atol=1e-11
    )


# ---------------------------------------------------------------------------
# certification


def test_certify_sketch_ok_and_singular(rng):
    M = jnp.asarray(rng.normal(size=(64, 8)))
    cert = guard.certify_sketch(M)
    assert cert.ok and cert.verdict == guard.OK
    assert cert.cond is not None and cert.cond < 1e3
    # rank collapse → RESKETCH (the bad_sketch_at injection shape)
    bad = M.at[1:].set(0.0)
    cert_bad = guard.certify_sketch(bad)
    assert cert_bad.verdict == guard.RESKETCH


def test_certify_sketch_nonfinite(rng):
    M = jnp.full((16, 4), jnp.nan)
    cert = guard.certify_sketch(M)
    assert cert.verdict == guard.RESKETCH
    assert "non-finite" in cert.detail


def test_certify_svd_posterior(rng):
    A = jnp.asarray(rng.normal(size=(40, 12)))
    U, s, Vt = jnp.linalg.svd(A, full_matrices=False)
    cert = guard.certify_svd(A, U, s, Vt.T)
    assert cert.ok
    # corrupt the leading left vector → posterior residual blows up
    cert_bad = guard.certify_svd(A, -U, s, Vt.T)
    assert cert_bad.verdict == guard.RESKETCH


def test_pinv_psd_solve_matches_cholesky(rng):
    Z = jnp.asarray(rng.normal(size=(50, 6)))
    G = Z.T @ Z + 0.1 * jnp.eye(6)
    C = jnp.asarray(rng.normal(size=(6, 2)))
    X = guard.pinv_psd_solve(G, C)
    np.testing.assert_allclose(
        np.asarray(G @ X), np.asarray(C), rtol=1e-8, atol=1e-8
    )


# ---------------------------------------------------------------------------
# ladder mechanics


def test_derived_context_distinct_and_deterministic():
    ctx = SketchContext(seed=42)
    seeds = {guard.derived_context(ctx, i).seed for i in range(1, 5)}
    assert len(seeds) == 4 and 42 not in seeds
    assert (
        guard.derived_context(ctx, 2).seed
        == guard.derived_context(SketchContext(seed=42), 2).seed
    )


def test_run_ladder_growth_and_fallback():
    calls = []

    def attempt(ctx, s, i):
        calls.append((int(ctx.seed), s, i))
        return None, guard.Certificate(guard.RESKETCH, "t", detail="no")

    result, report = guard.run_ladder(
        "t", SketchContext(seed=1), 10, 100, attempt, lambda: "dense",
        max_retries=3,
    )
    assert result == "dense"
    # initial, resketch (same size), two grows (geometric), then fallback
    assert [c[1] for c in calls] == [10, 10, 20, 40]
    assert calls[0][0] == 1 and len({c[0] for c in calls}) == 4
    d = report.to_dict()
    assert d["recovered"] is True
    assert [a["action"] for a in d["attempts"]] == [
        "initial", "resketch", "grow", "grow", "fallback",
    ]


def test_run_ladder_exhaustion_raises_without_fallback():
    def attempt(ctx, s, i):
        return None, guard.Certificate(guard.RESKETCH, "t")

    with pytest.raises(NumericalHealthError) as ei:
        guard.run_ladder(
            "t", SketchContext(seed=1), 4, 8, attempt, None, max_retries=1
        )
    assert ei.value.report is not None
    assert len(ei.value.report.attempts) == 2


# ---------------------------------------------------------------------------
# end-to-end recovery: in-core sketch-and-solve (acceptance criteria)


@pytest.mark.parametrize("fault", ["bad_sketch_at", "nan_at"])
def test_approximate_ls_recovers_from_injected_fault(rng, fault):
    A, b = _ls_problem(rng)
    ctx = lambda: SketchContext(seed=11)
    x_clean, info_clean = approximate_least_squares(
        A, b, ctx(), return_info=True
    )
    assert info_clean["recovery"]["attempts"][0]["verdict"] == guard.OK
    assert info_clean["recovery"]["recovered"] is False

    plan = FaultPlan(**{fault: 0})
    x_rec, info = approximate_least_squares(
        A, b, ctx(), fault_plan=plan, return_info=True
    )
    rec = info["recovery"]
    assert rec["recovered"] is True
    assert rec["attempts"][0]["verdict"] == guard.RESKETCH
    assert rec["attempts"][1]["action"] == "resketch"
    assert rec["attempts"][1]["verdict"] == guard.OK
    # Solution quality matches the fault-free run: both are sketch-and-
    # solve answers to the same planted problem, compare residuals.
    assert np.isfinite(np.asarray(x_rec)).all()
    assert _residual(A, x_rec, b) <= 1.5 * _residual(A, x_clean, b) + 1e-9


def test_approximate_ls_ladder_reaches_dense_fallback(rng):
    A, b = _ls_problem(rng)
    # Exhaust every sketch attempt (0 retries + a faulted attempt 0) so
    # the dense rung answers; it must match the exact solution.
    x, info = approximate_least_squares(
        A, b, SketchContext(seed=13),
        fault_plan=FaultPlan(nan_at=0), return_info=True,
    )
    x_exact = exact_least_squares(A, b, alg="svd")
    rec = info["recovery"]
    if rec["attempts"][-1]["action"] == "fallback":
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(x_exact), rtol=1e-8, atol=1e-8
        )
    else:  # recovered earlier on the ladder — still a valid solve
        assert _residual(A, x, b) <= 1.5 * _residual(A, x_exact, b) + 1e-9


def test_approximate_ls_fallback_when_retries_zero(rng, monkeypatch):
    monkeypatch.setenv("SKYLARK_GUARD_MAX_RETRIES", "0")
    A, b = _ls_problem(rng)
    x, info = approximate_least_squares(
        A, b, SketchContext(seed=13),
        fault_plan=FaultPlan(nan_at=0), return_info=True,
    )
    rec = info["recovery"]
    assert [a["action"] for a in rec["attempts"]] == ["initial", "fallback"]
    np.testing.assert_allclose(
        np.asarray(x), np.asarray(exact_least_squares(A, b, alg="svd")),
        rtol=1e-8, atol=1e-8,
    )


def test_guard_bypass_env(rng, monkeypatch):
    A, b = _ls_problem(rng)
    ctx = lambda: SketchContext(seed=17)
    x_on = approximate_least_squares(A, b, ctx())
    monkeypatch.setenv("SKYLARK_GUARD", "0")
    x_off, info = approximate_least_squares(A, b, ctx(), return_info=True)
    assert info["recovery"]["guarded"] is False
    assert info["recovery"]["attempts"] == []
    # guarding is bit-transparent on healthy runs
    np.testing.assert_array_equal(np.asarray(x_on), np.asarray(x_off))
    # bypassed + faulted = the silent-NaN behavior the guard exists for
    x_bad = approximate_least_squares(
        A, b, ctx(), fault_plan=FaultPlan(nan_at=0)
    )
    assert not np.isfinite(np.asarray(x_bad)).all()


def test_guard_parity_healthy_run(rng, monkeypatch):
    """Attempt 0 must reuse the caller's context: guarded == unguarded
    bit-for-bit when the certificate passes."""
    A, b = _ls_problem(rng)
    x_on = approximate_least_squares(A, b, SketchContext(seed=23))
    monkeypatch.setenv("SKYLARK_GUARD", "false")
    x_off = approximate_least_squares(A, b, SketchContext(seed=23))
    np.testing.assert_array_equal(np.asarray(x_on), np.asarray(x_off))


# ---------------------------------------------------------------------------
# end-to-end recovery: streaming (acceptance criteria)


def _stream_factory(A, b, nbatches):
    rows = A.shape[0] // nbatches

    def factory(start):
        return iter(
            [
                (
                    jnp.asarray(A[i * rows : (i + 1) * rows]),
                    jnp.asarray(b[i * rows : (i + 1) * rows]),
                )
                for i in range(start, nbatches)
            ]
        )

    return factory


@pytest.mark.streaming
@pytest.mark.parametrize("fault", ["bad_sketch_at", "nan_at"])
def test_streaming_ls_replays_poisoned_batch(rng, fault):
    m, n, nb = 240, 6, 8
    A = rng.normal(size=(m, n))
    b = A @ rng.normal(size=n) + 1e-3 * rng.normal(size=m)
    factory = _stream_factory(A, b, nb)
    x0, info0 = streaming_least_squares(
        factory, m, n, SketchContext(seed=3)
    )
    assert info0["recovery"]["recovered"] is False
    plan = FaultPlan(**{fault: 3})
    x1, info1 = streaming_least_squares(
        factory, m, n, SketchContext(seed=3), fault_plan=plan
    )
    rec = info1["recovery"]
    assert rec["recovered"] is True
    assert any(a["action"] == "replay" for a in rec["attempts"])
    # One-shot fault + chunk replay = bit-identical to the clean pass.
    np.testing.assert_array_equal(np.asarray(x0), np.asarray(x1))


@pytest.mark.streaming
def test_streaming_ls_unrecoverable_raises(rng):
    """A fault that is NOT one-shot (poison re-applied on replay) must
    surface as NumericalHealthError, not silent NaNs."""
    m, n, nb = 120, 4, 4

    class StickyPlan(FaultPlan):
        def _fire(self, kind, scheduled, index):
            return scheduled is not None and index == scheduled

    A = rng.normal(size=(m, n))
    b = rng.normal(size=m)
    with pytest.raises(NumericalHealthError):
        streaming_least_squares(
            _stream_factory(A, b, nb), m, n, SketchContext(seed=3),
            fault_plan=StickyPlan(nan_at=1),
        )


@pytest.mark.streaming
def test_streaming_krr_replays_poisoned_batch(rng):
    from libskylark_tpu.ml.kernels import GaussianKernel
    from libskylark_tpu.ml.krr import streaming_approximate_kernel_ridge

    n, d, nb = 160, 4, 8
    X = rng.normal(size=(n, d))
    y = rng.normal(size=n)
    kernel = GaussianKernel(d, 1.0)
    rows = n // nb

    def factory(start):
        return iter(
            [
                (
                    jnp.asarray(X[i * rows : (i + 1) * rows]),
                    jnp.asarray(y[i * rows : (i + 1) * rows]),
                )
                for i in range(start, nb)
            ]
        )

    m0 = streaming_approximate_kernel_ridge(
        kernel, factory, 0.1, 32, SketchContext(seed=5)
    )
    m1 = streaming_approximate_kernel_ridge(
        kernel, factory, 0.1, 32, SketchContext(seed=5),
        fault_plan=FaultPlan(nan_at=2),
    )
    assert m1.info["recovery"]["recovered"] is True
    assert any(
        a["action"] == "replay" for a in m1.info["recovery"]["attempts"]
    )
    np.testing.assert_array_equal(np.asarray(m0.W), np.asarray(m1.W))


# ---------------------------------------------------------------------------
# satellite: ne silent-NaN fix


def test_exact_ls_ne_rank_deficient_no_silent_nans(rng):
    A4 = rng.normal(size=(60, 4))
    A = jnp.asarray(np.concatenate([A4, A4], axis=1))  # rank 4 of 8
    b = jnp.asarray(rng.normal(size=60))
    x = exact_least_squares(A, b, alg="ne")
    assert np.isfinite(np.asarray(x)).all()
    # and it solves the problem as well as the pseudoinverse path
    x_svd = exact_least_squares(A, b, alg="svd")
    assert _residual(A, x, b) <= _residual(A, x_svd, b) * (1 + 1e-8) + 1e-9


def test_exact_ls_ne_rank_deficient_under_jit(rng):
    A4 = rng.normal(size=(60, 4))
    A = jnp.asarray(np.concatenate([A4, A4], axis=1))
    b = jnp.asarray(rng.normal(size=60))
    x = jax.jit(lambda A, b: exact_least_squares(A, b, alg="ne"))(A, b)
    assert np.isfinite(np.asarray(x)).all()


def test_exact_ls_ne_raises_when_guard_off(rng, monkeypatch):
    monkeypatch.setenv("SKYLARK_GUARD", "0")
    A4 = rng.normal(size=(60, 4))
    A = jnp.asarray(np.concatenate([A4, A4], axis=1))
    b = jnp.asarray(rng.normal(size=60))
    with pytest.raises(NumericalHealthError) as ei:
        exact_least_squares(A, b, alg="ne")
    assert ei.value.stage == "exact_ls_ne"


def test_exact_ls_ne_well_conditioned_unchanged(rng):
    A = jnp.asarray(rng.normal(size=(60, 5)))
    b = jnp.asarray(rng.normal(size=60))
    x_ne = exact_least_squares(A, b, alg="ne")
    x_qr = exact_least_squares(A, b, alg="qr")
    np.testing.assert_allclose(
        np.asarray(x_ne), np.asarray(x_qr), rtol=1e-8, atol=1e-10
    )


# ---------------------------------------------------------------------------
# randomized SVD certification


def test_approximate_svd_healthy_certifies_ok(rng):
    from libskylark_tpu.linalg.svd import approximate_svd

    A = jnp.asarray(rng.normal(size=(80, 20)))
    (U, s, V), info = approximate_svd(
        A, 4, SketchContext(seed=9), return_info=True
    )
    rec = info["recovery"]
    assert rec["attempts"][0]["verdict"] == guard.OK
    assert rec["recovered"] is False
    assert np.isfinite(np.asarray(s)).all()


def test_approximate_svd_guard_off_parity(rng, monkeypatch):
    from libskylark_tpu.linalg.svd import approximate_svd

    A = jnp.asarray(rng.normal(size=(80, 20)))
    U1, s1, V1 = approximate_svd(A, 4, SketchContext(seed=9))
    monkeypatch.setenv("SKYLARK_GUARD", "0")
    U2, s2, V2 = approximate_svd(A, 4, SketchContext(seed=9))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    np.testing.assert_array_equal(np.asarray(U1), np.asarray(U2))


# ---------------------------------------------------------------------------
# solver info plumbing


def test_blendenpik_info_recovery(rng):
    from libskylark_tpu.solvers.accelerated import faster_least_squares

    A, b = _ls_problem(rng)
    X, info = faster_least_squares(A, b, SketchContext(seed=19))
    rec = info["recovery"]
    assert rec["guarded"] is True
    assert rec["attempts"][0]["action"] == "initial"
    assert rec["attempts"][0]["verdict"] in (guard.OK, guard.RESKETCH)


def test_lsrn_info_recovery(rng):
    from libskylark_tpu.solvers.accelerated import lsrn_least_squares

    A, b = _ls_problem(rng)
    X, info = lsrn_least_squares(A, b, SketchContext(seed=19))
    assert info["recovery"]["guarded"] is True
    assert np.isfinite(np.asarray(X)).all()


def test_approximate_krr_info_recovery(rng):
    from libskylark_tpu.ml.kernels import GaussianKernel
    from libskylark_tpu.ml.krr import approximate_kernel_ridge

    X = jnp.asarray(rng.normal(size=(80, 4)))
    y = jnp.asarray(rng.normal(size=80))
    model = approximate_kernel_ridge(
        GaussianKernel(4, 1.0), X, y, 0.1, 16, SketchContext(seed=29)
    )
    assert model.info["recovery"]["guarded"] is True
    assert np.isfinite(np.asarray(model.W)).all()


def test_guard_config_knobs(monkeypatch):
    assert guard.enabled()
    monkeypatch.setenv("SKYLARK_GUARD", "0")
    assert not guard.enabled()
    monkeypatch.setenv("SKYLARK_GUARD", "1")
    assert guard.enabled()
    monkeypatch.setenv("SKYLARK_GUARD_MAX_RETRIES", "7")
    assert guard.max_retries() == 7
    monkeypatch.setenv("SKYLARK_GUARD_COND_MAX", "123.5")
    assert guard.cond_max() == 123.5
