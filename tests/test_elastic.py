"""Multi-host elastic streaming tests — the single-process tier.

Everything a ``jax.distributed`` world does that can be verified inside
one process is verified here: RowPartition arithmetic, world=1 bitwise
parity with the plain streaming drivers (the elastic route must not
perturb PR-5 bit-identity), simulated multi-rank folds through
``elastic_run_stream`` merged by hand (partial-sum parity + per-host
ledger/manifest contents), the typed code-109 resume guards (manifest
mismatch AND world-resolution mismatch), and single-rank kill-and-resume
bit-identity with ledger replay accounting.  The REAL multi-process
kill-one-rank scenario lives in ``tests/test_distributed.py`` (slow
tier).
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import sketch as sk
from libskylark_tpu import streaming
from libskylark_tpu.core import SketchContext
from libskylark_tpu.parallel import cross_host_psum
from libskylark_tpu.plans import accumulate_slice
from libskylark_tpu.sketch.base import Dimension
from libskylark_tpu.streaming import (
    ElasticParams,
    HostLedger,
    RowPartition,
    StreamParams,
    elastic_run_stream,
    host_dir,
    read_progress,
    skip_batches,
    world_info,
)
from libskylark_tpu.streaming.elastic import MANIFEST_NAME, PROGRESS_NAME
from libskylark_tpu.utils.exceptions import (
    InvalidParameters,
    WorldMismatchError,
)

pytestmark = pytest.mark.streaming

N, M, S_OUT = 60, 5, 16
BATCH = 7  # 60/7 -> 9 batches, last one ragged (4 rows)


def make_matrix(rng, n=N, m=M):
    return jnp.asarray(rng.standard_normal((n, m)))


def blocks_of(*arrays, batch=BATCH):
    n = arrays[0].shape[0]
    out = []
    for lo in range(0, n, batch):
        sl = tuple(a[lo : lo + batch] for a in arrays)
        out.append(sl[0] if len(arrays) == 1 else sl)
    return out


def factory_of(*arrays, batch=BATCH):
    def factory(start):
        it = iter(blocks_of(*arrays, batch=batch))
        return skip_batches(it, start) if start else it

    return factory


# ---------------------------------------------------------------------------
# RowPartition arithmetic
# ---------------------------------------------------------------------------


class TestRowPartition:
    @pytest.mark.parametrize(
        "nrows,batch_rows,world",
        [(60, 7, 1), (60, 7, 2), (60, 7, 4), (60, 7, 9), (60, 7, 16),
         (64, 8, 3), (1, 1, 1), (5, 100, 2)],
    )
    def test_batch_ranges_partition_the_stream(self, nrows, batch_rows,
                                               world):
        p = RowPartition(nrows=nrows, batch_rows=batch_rows,
                         world_size=world)
        ranges = [p.batch_range(r) for r in range(world)]
        # contiguous, ordered, covering [0, num_batches) exactly
        assert ranges[0][0] == 0
        assert ranges[-1][1] == p.num_batches
        for (a0, a1), (b0, b1) in zip(ranges, ranges[1:]):
            assert a1 == b0
        # balanced: sizes differ by at most one, extras go to low ranks
        sizes = [b1 - b0 for b0, b1 in ranges]
        assert max(sizes) - min(sizes) <= 1
        assert sizes == sorted(sizes, reverse=True)

    def test_row_ranges_cover_rows_with_ragged_tail(self):
        p = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        assert p.num_batches == 9
        r = [p.row_range(i) for i in range(2)]
        assert r[0] == (0, 5 * BATCH)  # rank 0 takes the extra batch
        assert r[1] == (5 * BATCH, N)  # ragged tail lands on the last rank
        assert r[1][1] - r[1][0] == 4 * BATCH - (9 * BATCH - N)

    def test_every_process_computes_the_same_split(self):
        a = RowPartition(nrows=1000, batch_rows=32, world_size=5)
        b = RowPartition.from_json(json.loads(json.dumps(a.to_json())))
        assert a == b
        assert a.signature() == b.signature()

    def test_signature_distinguishes_partitions(self):
        base = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        for other in (
            RowPartition(nrows=N, batch_rows=BATCH, world_size=4),
            RowPartition(nrows=N, batch_rows=BATCH + 1, world_size=2),
            RowPartition(nrows=N + 1, batch_rows=BATCH, world_size=2),
        ):
            assert other.signature() != base.signature()

    def test_invalid_parameters_rejected(self):
        with pytest.raises(InvalidParameters):
            RowPartition(nrows=0, batch_rows=BATCH, world_size=1)
        with pytest.raises(InvalidParameters):
            RowPartition(nrows=N, batch_rows=-1, world_size=1)
        with pytest.raises(InvalidParameters):
            RowPartition(nrows=N, batch_rows=BATCH, world_size=0)
        p = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        with pytest.raises(InvalidParameters):
            p.batch_range(2)

    def test_validate_world_is_the_typed_109_guard(self):
        p = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        p.validate_world(0, 2)
        p.validate_world(1, 2)
        with pytest.raises(WorldMismatchError) as ei:
            p.validate_world(0, 4)
        assert ei.value.code == 109
        assert ei.value.expected == 2
        assert ei.value.got == 4
        with pytest.raises(WorldMismatchError):
            p.validate_world(3, 2)


# ---------------------------------------------------------------------------
# world=1 parity: the elastic route must be bitwise the plain route
# ---------------------------------------------------------------------------


class TestSingleProcessParity:
    def test_distributed_sketch_is_bitwise_plain_sketch(self, rng):
        ctx = SketchContext(seed=21)
        S = sk.JLT(N, S_OUT, ctx)
        A = make_matrix(rng)
        want = streaming.sketch(factory_of(A), S, "columnwise", ncols=M)
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        got = streaming.sketch(
            factory_of(A), S, "columnwise", ncols=M, partition=part
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_distributed_lsq_is_bitwise_plain_lsq(self, rng):
        ctx = lambda: SketchContext(seed=22)  # noqa: E731
        A = make_matrix(rng)
        b = jnp.asarray(rng.standard_normal(N))

        def run(ctx_, partition):
            S = sk.CWT(N, S_OUT, ctx_)
            return streaming.sketch_least_squares(
                factory_of(A, b), S, ncols=M, partition=partition
            )

        want, winfo = run(ctx(), None)
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        got, ginfo = run(ctx(), part)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert ginfo["rows"] == winfo["rows"] == N
        assert ginfo["batches"] == winfo["batches"] == 9
        assert ginfo["local_batches"] == 9
        assert ginfo["world_size"] == 1 and ginfo["rank"] == 0

    def test_rowwise_partition_rejected(self, rng):
        ctx = SketchContext(seed=23)
        S = sk.JLT(M, S_OUT, ctx)
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        with pytest.raises(ValueError, match="columnwise-only"):
            streaming.sketch(
                factory_of(make_matrix(rng)), S, "rowwise", partition=part
            )

    def test_partition_route_requires_ncols(self, rng):
        ctx = SketchContext(seed=23)
        S = sk.JLT(N, S_OUT, ctx)
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        with pytest.raises(ValueError, match="ncols"):
            streaming.sketch(
                factory_of(make_matrix(rng)), S, "columnwise",
                partition=part,
            )

    def test_simulated_world_rejected_by_merge_drivers(self, rng):
        # The drivers psum-merge; a world_size>1 partition in a single
        # process would return an unmerged partial as if global.
        ctx = SketchContext(seed=24)
        S = sk.JLT(N, S_OUT, ctx)
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        with pytest.raises(InvalidParameters, match="live jax.distributed"):
            streaming.sketch(
                factory_of(make_matrix(rng)), S, "columnwise", ncols=M,
                partition=part,
            )

    def test_cross_host_psum_is_identity_at_world_one(self, rng):
        tree = {"sa": jnp.asarray(rng.standard_normal((3, 4))),
                "sb": jnp.asarray(rng.standard_normal((3, 1)))}
        out = cross_host_psum(tree)
        assert set(out) == {"sa", "sb"}
        np.testing.assert_array_equal(np.asarray(out["sa"]),
                                      np.asarray(tree["sa"]))
        np.testing.assert_array_equal(np.asarray(out["sb"]),
                                      np.asarray(tree["sb"]))


# ---------------------------------------------------------------------------
# simulated ranks: per-rank folds + hand merge, ledgers, manifests
# ---------------------------------------------------------------------------


def _rank_fold(A, S, part, rank, root, *, resume=False, fault_plan=None,
               checkpoint_every=1):
    """One simulated rank's partial fold of columnwise S·A."""
    r0, _ = part.row_range(rank)
    init = {
        "sa": jnp.zeros((S.s, A.shape[1]), jnp.float64),
        "row": np.asarray(r0, np.int64),
    }

    def step(acc, block, index):
        row = int(acc["row"])
        return {
            "sa": accumulate_slice(S, acc["sa"], block, row),
            "row": np.asarray(row + block.shape[0], np.int64),
        }

    params = ElasticParams(
        rank=rank, world_size=part.world_size,
        checkpoint_dir=str(root) if root is not None else None,
        checkpoint_every=checkpoint_every, resume=resume, prefetch=0,
    )
    return elastic_run_stream(
        factory_of(A), step, init, part, params, fault_plan=fault_plan
    )


class TestSimulatedRanks:
    def test_two_rank_merge_matches_in_core_apply(self, tmp_path, rng):
        ctx = SketchContext(seed=31)
        S = sk.JLT(N, S_OUT, ctx)
        A = make_matrix(rng)
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        partials = []
        for rank in range(2):
            acc, nbatches = _rank_fold(A, S, part, rank, tmp_path)
            b0, b1 = part.batch_range(rank)
            assert nbatches == b1 - b0
            r0, r1 = part.row_range(rank)
            assert int(acc["row"]) == r1
            partials.append(acc["sa"])
        merged = S.finalize_slices(partials[0] + partials[1],
                                   Dimension.COLUMNWISE)
        want = S.apply(A, Dimension.COLUMNWISE)
        np.testing.assert_allclose(
            np.asarray(merged), np.asarray(want), rtol=1e-10, atol=1e-10
        )

    def test_per_host_ledger_records_owned_batches(self, tmp_path, rng):
        ctx = SketchContext(seed=32)
        S = sk.JLT(N, S_OUT, ctx)
        A = make_matrix(rng)
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        for rank in range(2):
            _rank_fold(A, S, part, rank, tmp_path)
        for rank in range(2):
            hdir = host_dir(tmp_path, rank)
            recs = read_progress(os.path.join(hdir, PROGRESS_NAME))
            b0, b1 = part.batch_range(rank)
            folded = [r["attrs"]["batch"] for r in recs
                      if r["name"] == "batch"]
            assert folded == list(range(b0, b1))
            locals_ = [r["attrs"]["local"] for r in recs
                       if r["name"] == "batch"]
            assert locals_ == list(range(b1 - b0))
            done = [r for r in recs if r["name"] == "done"]
            assert len(done) == 1
            assert done[0]["attrs"]["batches"] == b1 - b0
            # telemetry run-ledger schema, per-host manifest
            for r in recs:
                assert set(r) == {"ts", "seq", "pid", "kind", "name",
                                  "attrs"}
                assert r["kind"] == "elastic"
                assert r["attrs"]["rank"] == rank
            with open(os.path.join(hdir, MANIFEST_NAME)) as fh:
                man = json.load(fh)
            assert man["rank"] == rank
            assert man["signature"] == part.signature()
            assert man["partition"] == part.to_json()

    def test_kill_one_rank_resume_is_bit_identical(self, tmp_path, rng):
        from libskylark_tpu.resilient import FaultPlan, SimulatedPreemption

        ctx = SketchContext(seed=33)
        S = sk.JLT(N, S_OUT, ctx)
        A = make_matrix(rng)
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        victim = 1
        # uninterrupted reference fold for the victim rank
        want_acc, _ = _rank_fold(A, S, part, victim, tmp_path / "ref")
        # killed fold: checkpoint every batch, die after chunk 1
        root = tmp_path / "elastic"
        with pytest.raises(SimulatedPreemption):
            _rank_fold(
                A, S, part, victim, root,
                fault_plan=FaultPlan(preempt_after_chunk=1),
            )
        hdir = host_dir(root, victim)
        killed = read_progress(os.path.join(hdir, PROGRESS_NAME))
        folded_before = [r["attrs"]["batch"] for r in killed
                         if r["name"] == "batch"]
        assert folded_before  # died mid-stream, after some progress
        assert not [r for r in killed if r["name"] == "done"]
        # restart with resume: only the uncheckpointed tail re-folds
        got_acc, nbatches = _rank_fold(
            A, S, part, victim, root, resume=True
        )
        b0, b1 = part.batch_range(victim)
        assert nbatches == b1 - b0
        np.testing.assert_array_equal(
            np.asarray(got_acc["sa"]), np.asarray(want_acc["sa"])
        )
        recs = read_progress(os.path.join(hdir, PROGRESS_NAME))
        replayed = [r["attrs"]["batch"] for r in recs[len(killed):]
                    if r["name"] == "batch"]
        # checkpoint_every=1: both committed chunks (batches b0, b0+1)
        # are on disk, so the resume replays exactly the tail
        assert replayed == list(range(b0 + 2, b1))
        assert [r for r in recs if r["name"] == "done"]

    def test_resume_under_different_world_raises_109(self, tmp_path, rng):
        ctx = SketchContext(seed=34)
        S = sk.JLT(N, S_OUT, ctx)
        A = make_matrix(rng)
        part2 = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        _rank_fold(A, S, part2, 0, tmp_path)
        # same host dir, resumed for a world of 4: manifest mismatch
        part4 = RowPartition(nrows=N, batch_rows=BATCH, world_size=4)
        with pytest.raises(WorldMismatchError) as ei:
            _rank_fold(A, S, part4, 0, tmp_path, resume=True)
        assert ei.value.code == 109
        assert ei.value.expected["signature"] == part2.signature()
        assert ei.value.got["signature"] == part4.signature()

    def test_resume_under_different_row_partition_raises_109(
        self, tmp_path, rng
    ):
        ctx = SketchContext(seed=35)
        S = sk.JLT(N, S_OUT, ctx)
        A = make_matrix(rng)
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        _rank_fold(A, S, part, 0, tmp_path)
        repart = RowPartition(nrows=N, batch_rows=BATCH + 3, world_size=2)
        with pytest.raises(WorldMismatchError) as ei:
            _rank_fold(A, S, repart, 0, tmp_path, resume=True)
        assert ei.value.code == 109

    def test_world_resolution_mismatch_raises_109_without_disk(self, rng):
        # the validate_world half of the guard: no checkpoint dir at all
        ctx = SketchContext(seed=36)
        S = sk.JLT(N, S_OUT, ctx)
        A = make_matrix(rng)
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        init = {"sa": jnp.zeros((S.s, M), jnp.float64),
                "row": np.asarray(0, np.int64)}
        with pytest.raises(WorldMismatchError) as ei:
            elastic_run_stream(
                factory_of(A), lambda a, b, i: a, init, part,
                ElasticParams(rank=0, world_size=3, prefetch=0),
            )
        assert ei.value.code == 109

    def test_world_info_single_process(self):
        rank, world = world_info()
        assert (rank, world) == (0, 1)


# ---------------------------------------------------------------------------
# HostLedger contract
# ---------------------------------------------------------------------------


class TestHostLedger:
    def test_schema_and_seq_continuation(self, tmp_path):
        path = tmp_path / "progress.jsonl"
        led = HostLedger(path, rank=3, epoch=2)
        led.record("batch", batch=7, local=0)
        led.record("done", batches=1)
        led.close()
        recs = read_progress(path)
        assert [r["seq"] for r in recs] == [1, 2]
        assert all(r["kind"] == "elastic" for r in recs)
        assert recs[0]["attrs"] == {"rank": 3, "epoch": 2, "batch": 7,
                                    "local": 0}
        # a restarted incarnation keeps the per-host total order
        led2 = HostLedger(path, rank=3, epoch=2)
        led2.record("batch", batch=8, local=1)
        led2.close()
        recs = read_progress(path)
        assert [r["seq"] for r in recs] == [1, 2, 3]

    def test_read_progress_tolerates_torn_tail(self, tmp_path):
        path = tmp_path / "progress.jsonl"
        led = HostLedger(path, rank=0)
        led.record("batch", batch=0, local=0)
        led.close()
        with open(path, "a", encoding="utf-8") as fh:
            fh.write('{"ts": 1.0, "seq": 2, "pid": 1, "ki')  # SIGKILL tear
        recs = read_progress(path)
        assert len(recs) == 1
        # and the next incarnation continues from the last INTACT seq
        led2 = HostLedger(path, rank=0)
        assert led2.record("done", batches=1) == 2
        led2.close()

    def test_missing_file_reads_empty(self, tmp_path):
        assert read_progress(tmp_path / "absent.jsonl") == []

    def test_duplicate_seq_keeps_last_record(self, tmp_path):
        # A rank that dies after write() but before its ledger line is
        # acknowledged can replay the same batch and re-record the same
        # seq on resume; the LAST occurrence is the authoritative one.
        path = tmp_path / "progress.jsonl"
        lines = [
            '{"ts": 1.0, "seq": 1, "kind": "elastic",'
            ' "attrs": {"rank": 0, "epoch": 0, "batch": 4}}',
            '{"ts": 2.0, "seq": 1, "kind": "elastic",'
            ' "attrs": {"rank": 0, "epoch": 0, "batch": 5}}',
        ]
        path.write_text("\n".join(lines) + "\n", encoding="utf-8")
        recs = read_progress(path)
        assert len(recs) == 1
        assert recs[0]["attrs"]["batch"] == 5

    def test_out_of_order_seq_returns_sorted(self, tmp_path):
        # Buffered writes flushed by two racing incarnations can land
        # out of order on shared storage; readers see seq order.
        path = tmp_path / "progress.jsonl"
        lines = [
            '{"ts": 1.0, "seq": 3, "kind": "elastic",'
            ' "attrs": {"rank": 0, "epoch": 0, "batch": 2}}',
            '{"ts": 1.0, "seq": 1, "kind": "elastic",'
            ' "attrs": {"rank": 0, "epoch": 0, "batch": 0}}',
            '{"ts": 1.0, "seq": 2, "kind": "elastic",'
            ' "attrs": {"rank": 0, "epoch": 0, "batch": 1}}',
        ]
        path.write_text("\n".join(lines) + "\n", encoding="utf-8")
        recs = read_progress(path)
        assert [r["seq"] for r in recs] == [1, 2, 3]
        assert [r["attrs"]["batch"] for r in recs] == [0, 1, 2]
        # and the next incarnation continues past the highest intact seq
        led = HostLedger(path, rank=0)
        assert led.record("done", batches=3) == 4
        led.close()

    def test_epoch_scopes_the_seq_space(self, tmp_path):
        # Same seq under different epochs = different incarnation
        # generations, NOT duplicates; both survive, epoch-major order.
        path = tmp_path / "progress.jsonl"
        lines = [
            '{"ts": 1.0, "seq": 1, "kind": "elastic",'
            ' "attrs": {"rank": 0, "epoch": 1, "batch": 9}}',
            '{"ts": 1.0, "seq": 1, "kind": "elastic",'
            ' "attrs": {"rank": 0, "epoch": 0, "batch": 0}}',
        ]
        path.write_text("\n".join(lines) + "\n", encoding="utf-8")
        recs = read_progress(path)
        assert [(r["attrs"]["epoch"], r["seq"]) for r in recs] == [
            (0, 1), (1, 1)
        ]

    def test_non_dict_json_lines_are_skipped(self, tmp_path):
        path = tmp_path / "progress.jsonl"
        path.write_text(
            '42\n'
            '"noise"\n'
            '{"ts": 1.0, "seq": 1, "kind": "elastic",'
            ' "attrs": {"rank": 0, "epoch": 0}}\n',
            encoding="utf-8",
        )
        recs = read_progress(path)
        assert len(recs) == 1 and recs[0]["seq"] == 1
