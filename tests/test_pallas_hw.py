"""Hardware-gated numerics guards (VERDICT r1 item 5, consolidated r4).

The regular suite pins the CPU backend in ``conftest.py``, so compiled
(non-interpret) kernels are exercised from ONE subprocess that lets jax
pick its default backend (``tests/_hw_guards.py``).  On the bench chip
that is the TPU and all guards real-dispatch behind a single backend
init; anywhere else the child reports its backend and every test skips.

Failure taxonomy (VERDICT r3 weak #3): a guard ASSERTION failure fails
its test; a child TIMEOUT (congested axon tunnel — ~8×420 s under the
old per-guard-subprocess design) skips with a reason, because tunnel
weather is environmental, not a numerics regression.  Worst case is one
child timeout ≈ 8.5 min, under the 10-minute budget.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TIMEOUT_S = 510

_GUARD_NAMES = [
    "rfut_rowwise_compiled",
    "pallas_scatter_compiled",
    "pallas_window_compiled",
    "fjlt_sampled_compiled",
    "bf16_split_accuracy",
    "wht_f32_accuracy",
    "psd_gram_precision",
    "streaming_svd_orthogonality",
    "frft_realized_split",
    "mmt_scaled_onehot_split",
    "fjlt_pallas_branch_compiled",
]


def _metadata_answers() -> bool:
    """One cheap GET against the GCE metadata server's ``tpu-env`` key.

    True only when it answers fast — the case where libtpu's own tpu-env
    queries inside backend init are also fast.  A server that 403s (or a
    host with no metadata route) makes libtpu retry EVERY variable 30
    times: ~8.5 min of stall inside the guard child, which alone eats
    the whole tier-1 wall budget on a TPU-less box.
    """
    import urllib.request

    try:
        urllib.request.urlopen(
            urllib.request.Request(
                "http://metadata.google.internal/computeMetadata/v1/"
                "instance/attributes/tpu-env",
                headers={"Metadata-Flavor": "Google"},
            ),
            timeout=3,
        ).read()
        return True
    except Exception:  # noqa: BLE001 — any miss means init would stall
        return False


@pytest.fixture(scope="module")
def guard_results():
    """Run every guard in one child process on the default backend.

    Returns ``{name: (status, detail)}`` with status in
    {"ok", "fail", "skip"}; the whole dict is built from one subprocess
    so the tunnel backend init is paid once for all guards.
    """
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    if not _metadata_answers():
        # Pre-probed: the metadata server would stall libtpu's init for
        # ~8.5 min before the CPU fallback.  Skip the query — a real TPU
        # VM whose metadata answers never takes this branch, and a box
        # with topology baked into env vars doesn't need the server.
        env.setdefault("TPU_SKIP_MDS_QUERY", "1")
    timed_out = False
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tests", "_hw_guards.py")],
            capture_output=True,
            text=True,
            timeout=_TIMEOUT_S,
            env=env,
            cwd=_REPO,
        )
        stdout, stderr, rc = out.stdout, out.stderr, out.returncode
    except subprocess.TimeoutExpired as e:
        # Keep the partial stdout: guards that already FAILED before the
        # hang are real regressions and must not be laundered into skips.
        timed_out = True
        stdout = e.stdout or ""
        stderr = e.stderr or ""
        if isinstance(stdout, bytes):
            stdout = stdout.decode(errors="replace")
        if isinstance(stderr, bytes):
            stderr = stderr.decode(errors="replace")
        rc = None
    results = {}
    for line in stdout.splitlines():
        if line.startswith("SKIP-NOT-TPU"):
            backend = line.split(None, 1)[1] if " " in line else "?"
            return {
                name: ("skip", f"default backend is not TPU: {backend}")
                for name in _GUARD_NAMES
            }
        if line.startswith("GUARD "):
            _, name, status, *rest = line.split(None, 3) + [""]
            results[name] = (
                "ok" if status == "OK" else "fail",
                rest[0] if rest else "",
            )
    for name in _GUARD_NAMES:
        if name in results:
            continue
        if timed_out:
            # No verdict before the tunnel hang — environmental.
            results[name] = (
                "skip",
                f"guard child timed out after {_TIMEOUT_S}s before this "
                "guard ran (congested tunnel / slow backend init)",
            )
        else:
            # The child died (crash, OOM) before reaching this guard —
            # that is a real failure, not tunnel weather.
            results[name] = (
                "fail",
                f"no result from guard child (rc={rc})\n"
                f"stdout:\n{stdout}\nstderr:\n{stderr[-2000:]}",
            )
    return results


def _check(guard_results, name):
    status, detail = guard_results[name]
    if status == "skip":
        pytest.skip(detail)
    assert status == "ok", f"hardware guard {name} failed: {detail}"


@pytest.mark.parametrize("name", _GUARD_NAMES)
def test_hw_guard(guard_results, name):
    _check(guard_results, name)
