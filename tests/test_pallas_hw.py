"""Hardware-gated Pallas real-dispatch tests (VERDICT r1 item 5).

The regular suite pins the CPU backend in ``conftest.py``, so the compiled
(non-interpret) kernels are exercised from a SUBPROCESS that lets jax pick
its default backend.  On the bench chip that is the TPU and the kernels
real-dispatch; anywhere else the subprocess reports its backend and the
tests skip.  This surfaces Mosaic lowering breakage in CI-on-hardware
rather than only inside bench runs.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_on_default_backend(code: str) -> str:
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
        cwd=_REPO,
    )
    if out.returncode != 0:
        raise AssertionError(
            f"subprocess failed\nstdout:\n{out.stdout}\nstderr:\n{out.stderr}"
        )
    return out.stdout


_PRELUDE = """
import jax
if jax.default_backend() != "tpu":
    print("SKIP-NOT-TPU", jax.default_backend())
    raise SystemExit(0)
import numpy as np
import jax.numpy as jnp
"""


def test_rfut_rowwise_compiled_on_tpu():
    out = _run_on_default_backend(
        _PRELUDE
        + """
from libskylark_tpu.sketch import pallas_fut, wht
rng = np.random.default_rng(0)
m, n, nb = 256, 512, 512
x = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
d = jnp.asarray(np.sign(rng.standard_normal(n)), jnp.float32)
out = pallas_fut.rfut_rowwise(x, d, nb, interpret=False)  # compiled
ref = wht(x * d[None, :], axis=1)
np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                           rtol=1e-4, atol=1e-4)
print("RFUT-COMPILED-OK")
"""
    )
    if "SKIP-NOT-TPU" in out:
        pytest.skip(f"default backend is not TPU: {out.strip()}")
    assert "RFUT-COMPILED-OK" in out


def test_bf16_split_accuracy_on_tpu():
    """The f32 hi/lo/lo2 bf16-split paths must keep ~f32 accuracy on
    hardware.  An astype-based split (``x - bf16(x)``) collapses to
    single-bf16 accuracy on TPU because XLA's excess-precision rules
    elide the f32→bf16→f32 convert pair, zeroing lo/lo2 (measured
    1.6e-3 max-rel vs 8e-8 for the bit-mask split in core/precision.py).
    CPU CI cannot see this — the elision fires in the TPU pipeline."""
    out = _run_on_default_backend(
        _PRELUDE
        + """
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.sketch.fjlt import FJLT
from libskylark_tpu.sketch.hash import CWT
rng = np.random.default_rng(0)
n, s, m = 1024, 256, 512
A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
S = FJLT(n, s, SketchContext(seed=3))
assert S._gemm_wins(jnp.float32)
out = np.asarray(jax.jit(lambda A: S._apply_srht_gemm(A, rowwise=True))(A),
                 np.float64)
G = np.asarray(S._srht_matrix(jnp.float32), np.float64)
ref = (np.asarray(A, np.float64) @ G) / np.sqrt(s)
rel = np.abs(out - ref).max() / np.abs(ref).max()
assert rel < 2e-5, f"FJLT split degraded on hardware: {rel}"
Sc = CWT(m, 64, SketchContext(seed=5))
outc = np.asarray(jax.jit(lambda A: Sc.apply(A, "columnwise"))(A), np.float64)
M = np.asarray(Sc._hash_matrix(jnp.float32), np.float64)
refc = M.T @ np.asarray(A, np.float64)
relc = np.abs(outc - refc).max() / np.abs(refc).max()
assert relc < 2e-5, f"CWT split degraded on hardware: {relc}"
print("SPLIT-ACCURACY-OK")
"""
    )
    if "SKIP-NOT-TPU" in out:
        pytest.skip(f"default backend is not TPU: {out.strip()}")
    assert "SPLIT-ACCURACY-OK" in out


def test_wht_f32_accuracy_on_tpu():
    """The f32 WHT (bf16-split chain on TPU) must match a host f64
    reference to ~f32 accuracy — guards both the MXU default-precision
    hazard and any future regression of the split."""
    out = _run_on_default_backend(
        _PRELUDE
        + """
from libskylark_tpu.sketch.fut import wht, _hadamard
rng = np.random.default_rng(2)
m, n = 256, 4096
x = rng.standard_normal((m, n)).astype(np.float32)
got = np.asarray(jax.jit(lambda x: wht(x, axis=1))(jnp.asarray(x)),
                 np.float64)
H = np.asarray(_hadamard(12), np.float64)
ref = (x.astype(np.float64) @ H.T) / np.sqrt(n)
rel = np.abs(got - ref).max() / np.abs(ref).max()
assert rel < 2e-5, f"wht f32 degraded on hardware: {rel}"
print("WHT-F32-OK")
"""
    )
    if "SKIP-NOT-TPU" in out:
        pytest.skip(f"default backend is not TPU: {out.strip()}")
    assert "WHT-F32-OK" in out


def test_psd_gram_precision_on_tpu():
    """`ml/krr.py::_psd_gram` pins precision='highest' because the MXU
    default truncates f32 operands to bf16 mantissas — enough to push a
    barely-regularized Gram off its f64 value by ~1e-2 relative and
    destabilize the Cholesky solves built on it.  Guards the pin: if it
    is removed, the relative check fails on hardware."""
    out = _run_on_default_backend(
        _PRELUDE
        + """
from libskylark_tpu.ml.krr import _psd_gram
rng = np.random.default_rng(3)
m, s = 4096, 256
Z = jnp.asarray(rng.standard_normal((m, s)), jnp.float32)
lam = jnp.float32(1e-4)
G = np.asarray(jax.jit(lambda Z: _psd_gram(Z.T, Z) + lam * jnp.eye(s))(Z),
               np.float64)
ref = np.asarray(Z, np.float64).T @ np.asarray(Z, np.float64) + 1e-4 * np.eye(s)
rel = np.abs(G - ref).max() / np.abs(ref).max()
assert rel < 2e-5, f"_psd_gram degraded on hardware: {rel}"
L = np.linalg.cholesky(G)  # PSD property survives
assert np.isfinite(L).all()
print("PSD-GRAM-OK")
"""
    )
    if "SKIP-NOT-TPU" in out:
        pytest.skip(f"default backend is not TPU: {out.strip()}")
    assert "PSD-GRAM-OK" in out


def test_streaming_svd_orthogonality_on_tpu():
    """Streaming SVD's CholeskyQR2 whitening repair relies on the pinned
    Gram products (linalg/svd.py); on hardware the f32 U must stay
    orthonormal to ~1e-3 (measured ~4e-4 round 1).  An un-pinned Gram
    sends this to ~1e-2."""
    out = _run_on_default_backend(
        _PRELUDE
        + """
from libskylark_tpu import SketchContext
from libskylark_tpu.linalg import (SVDParams, streaming_approximate_svd,
                                   synthetic_lowrank_blocks)
m, n, k, br = 100_000, 256, 20, 25_000
blocks = synthetic_lowrank_blocks(SketchContext(seed=5), m, n, k,
                                  noise=0.01, dtype=jnp.float32)
U, s, V = streaming_approximate_svd(blocks, (m, n), k, SketchContext(seed=6),
                                    SVDParams(num_iterations=1),
                                    block_rows=br, materialize_u=True)
G = np.asarray(jnp.dot(U.T, U, precision="highest"), np.float64)
err = np.abs(G - np.eye(k)).max()
assert err < 1.5e-3, f"streaming-SVD U lost orthogonality on hardware: {err}"
print("SVD-ORTHO-OK", err)
"""
    )
    if "SKIP-NOT-TPU" in out:
        pytest.skip(f"default backend is not TPU: {out.strip()}")
    assert "SVD-ORTHO-OK" in out


def test_frft_realized_split_on_tpu():
    """Fastfood's realized-W f32 path (4-pass bf16 split, round 3) vs
    the precision-pinned streaming form on hardware: ~2^-16-relative
    pre-cos ⇒ ≤5e-4 on the cos features.  A degraded split (astype
    elision) or a dropped WHT pin pushes this to ~1e-1/1e-2."""
    out = _run_on_default_backend(
        _PRELUDE
        + """
import os
from libskylark_tpu import SketchContext
from libskylark_tpu.sketch import FastGaussianRFT
rng = np.random.default_rng(4)
n, s, m = 512, 1024, 4096
A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
S = FastGaussianRFT(n, s, SketchContext(seed=7), sigma=2.0)
assert S._realize_wins(jnp.float32, m)
fast = np.asarray(S.apply(A, "rowwise"))
os.environ["SKYLARK_NO_FRFT_GEMM"] = "1"
ref = np.asarray(S.apply(A, "rowwise"))
err = np.abs(fast - ref).max()
assert err < 5e-4, f"FRFT realized split degraded on hardware: {err}"
print("FRFT-SPLIT-OK", err)
"""
    )
    if "SKIP-NOT-TPU" in out:
        pytest.skip(f"default backend is not TPU: {out.strip()}")
    assert "FRFT-SPLIT-OK" in out


def test_mmt_scaled_onehot_split_on_tpu():
    """MMT/WZT's scaled-one-hot f32 path (v folded into A, 0/1 matrix,
    3-pass split — round 3) vs the f64 host oracle on hardware."""
    out = _run_on_default_backend(
        _PRELUDE
        + """
from libskylark_tpu import SketchContext
from libskylark_tpu.sketch import MMT
rng = np.random.default_rng(5)
n, s, m = 1024, 128, 512
A = jnp.asarray(rng.standard_normal((n, m)), jnp.float32)
S = MMT(n, s, SketchContext(seed=9))
out_d = np.asarray(jax.jit(lambda A: S.apply(A, "columnwise"))(A), np.float64)
M = np.asarray(S._hash_matrix(jnp.float32), np.float64)
ref = M.T @ np.asarray(A, np.float64)
rel = np.abs(out_d - ref).max() / np.abs(ref).max()
assert rel < 5e-5, f"MMT scaled split degraded on hardware: {rel}"
print("MMT-SPLIT-OK", rel)
"""
    )
    if "SKIP-NOT-TPU" in out:
        pytest.skip(f"default backend is not TPU: {out.strip()}")
    assert "MMT-SPLIT-OK" in out


def test_fjlt_pallas_branch_compiled_on_tpu():
    out = _run_on_default_backend(
        _PRELUDE
        + """
import os
from libskylark_tpu import SketchContext
from libskylark_tpu.sketch import FJLT
rng = np.random.default_rng(1)
n, s, m = 512, 64, 256
A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
S1 = FJLT(n, s, SketchContext(seed=3))
out = S1.apply(A, "rowwise")  # gate picks a TPU path (pallas or gemm)
os.environ["SKYLARK_NO_PALLAS"] = "1"
os.environ["SKYLARK_NO_SRHT_GEMM"] = "1"
ref = S1.apply(A, "rowwise")  # forced XLA path, same transform
np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                           rtol=2e-3, atol=2e-3)
print("FJLT-TPU-OK")
"""
    )
    if "SKIP-NOT-TPU" in out:
        pytest.skip(f"default backend is not TPU: {out.strip()}")
    assert "FJLT-TPU-OK" in out
