"""Hardware-gated Pallas real-dispatch tests (VERDICT r1 item 5).

The regular suite pins the CPU backend in ``conftest.py``, so the compiled
(non-interpret) kernels are exercised from a SUBPROCESS that lets jax pick
its default backend.  On the bench chip that is the TPU and the kernels
real-dispatch; anywhere else the subprocess reports its backend and the
tests skip.  This surfaces Mosaic lowering breakage in CI-on-hardware
rather than only inside bench runs.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_on_default_backend(code: str) -> str:
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
        cwd=_REPO,
    )
    if out.returncode != 0:
        raise AssertionError(
            f"subprocess failed\nstdout:\n{out.stdout}\nstderr:\n{out.stderr}"
        )
    return out.stdout


_PRELUDE = """
import jax
if jax.default_backend() != "tpu":
    print("SKIP-NOT-TPU", jax.default_backend())
    raise SystemExit(0)
import numpy as np
import jax.numpy as jnp
"""


def test_rfut_rowwise_compiled_on_tpu():
    out = _run_on_default_backend(
        _PRELUDE
        + """
from libskylark_tpu.sketch import pallas_fut, wht
rng = np.random.default_rng(0)
m, n, nb = 256, 512, 512
x = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
d = jnp.asarray(np.sign(rng.standard_normal(n)), jnp.float32)
out = pallas_fut.rfut_rowwise(x, d, nb, interpret=False)  # compiled
ref = wht(x * d[None, :], axis=1)
np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                           rtol=1e-4, atol=1e-4)
print("RFUT-COMPILED-OK")
"""
    )
    if "SKIP-NOT-TPU" in out:
        pytest.skip(f"default backend is not TPU: {out.strip()}")
    assert "RFUT-COMPILED-OK" in out


def test_bf16_split_accuracy_on_tpu():
    """The f32 hi/lo/lo2 bf16-split paths must keep ~f32 accuracy on
    hardware.  An astype-based split (``x - bf16(x)``) collapses to
    single-bf16 accuracy on TPU because XLA's excess-precision rules
    elide the f32→bf16→f32 convert pair, zeroing lo/lo2 (measured
    1.6e-3 max-rel vs 8e-8 for the bit-mask split in core/precision.py).
    CPU CI cannot see this — the elision fires in the TPU pipeline."""
    out = _run_on_default_backend(
        _PRELUDE
        + """
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.sketch.fjlt import FJLT
from libskylark_tpu.sketch.hash import CWT
rng = np.random.default_rng(0)
n, s, m = 1024, 256, 512
A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
S = FJLT(n, s, SketchContext(seed=3))
assert S._gemm_wins(jnp.float32)
out = np.asarray(jax.jit(lambda A: S._apply_srht_gemm(A, rowwise=True))(A),
                 np.float64)
G = np.asarray(S._srht_matrix(jnp.float32), np.float64)
ref = (np.asarray(A, np.float64) @ G) / np.sqrt(s)
rel = np.abs(out - ref).max() / np.abs(ref).max()
assert rel < 2e-5, f"FJLT split degraded on hardware: {rel}"
Sc = CWT(m, 64, SketchContext(seed=5))
outc = np.asarray(jax.jit(lambda A: Sc.apply(A, "columnwise"))(A), np.float64)
M = np.asarray(Sc._hash_matrix(jnp.float32), np.float64)
refc = M.T @ np.asarray(A, np.float64)
relc = np.abs(outc - refc).max() / np.abs(refc).max()
assert relc < 2e-5, f"CWT split degraded on hardware: {relc}"
print("SPLIT-ACCURACY-OK")
"""
    )
    if "SKIP-NOT-TPU" in out:
        pytest.skip(f"default backend is not TPU: {out.strip()}")
    assert "SPLIT-ACCURACY-OK" in out


def test_wht_f32_accuracy_on_tpu():
    """The f32 WHT (bf16-split chain on TPU) must match a host f64
    reference to ~f32 accuracy — guards both the MXU default-precision
    hazard and any future regression of the split."""
    out = _run_on_default_backend(
        _PRELUDE
        + """
from libskylark_tpu.sketch.fut import wht, _hadamard
rng = np.random.default_rng(2)
m, n = 256, 4096
x = rng.standard_normal((m, n)).astype(np.float32)
got = np.asarray(jax.jit(lambda x: wht(x, axis=1))(jnp.asarray(x)),
                 np.float64)
H = np.asarray(_hadamard(12), np.float64)
ref = (x.astype(np.float64) @ H.T) / np.sqrt(n)
rel = np.abs(got - ref).max() / np.abs(ref).max()
assert rel < 2e-5, f"wht f32 degraded on hardware: {rel}"
print("WHT-F32-OK")
"""
    )
    if "SKIP-NOT-TPU" in out:
        pytest.skip(f"default backend is not TPU: {out.strip()}")
    assert "WHT-F32-OK" in out


def test_fjlt_pallas_branch_compiled_on_tpu():
    out = _run_on_default_backend(
        _PRELUDE
        + """
import os
from libskylark_tpu import SketchContext
from libskylark_tpu.sketch import FJLT
rng = np.random.default_rng(1)
n, s, m = 512, 64, 256
A = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
S1 = FJLT(n, s, SketchContext(seed=3))
out = S1.apply(A, "rowwise")  # gate picks a TPU path (pallas or gemm)
os.environ["SKYLARK_NO_PALLAS"] = "1"
os.environ["SKYLARK_NO_SRHT_GEMM"] = "1"
ref = S1.apply(A, "rowwise")  # forced XLA path, same transform
np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                           rtol=2e-3, atol=2e-3)
print("FJLT-TPU-OK")
"""
    )
    if "SKIP-NOT-TPU" in out:
        pytest.skip(f"default backend is not TPU: {out.strip()}")
    assert "FJLT-TPU-OK" in out
