"""Latency attribution, SLO error budgets, and the timeline ring
(ISSUE PR 20).

The acceptance contracts pinned here:

- A traced request's ``trace["phases"]`` decomposition sums to within
  10% of its own ``e2e_ms`` (the phases are consecutive diffs of one
  monotonic timestamp chain, so they tile the wall by construction).
- ``/metrics`` exposes real Prometheus 0.0.4 histogram families —
  cumulative ``_bucket{le="..."}`` rows with monotone counts, the
  ``+Inf`` bucket equal to ``_count`` — and the whole body survives a
  strict parse (name charset, TYPE-before-samples, two tokens a line).
- An induced slow-tenant drill drives ``slo.budget_remaining`` below
  the burn threshold and lands a ledgered ``slo_burn`` violation in the
  flight recorder's violations ring.
- ``SKYLARK_TELEMETRY=0`` runs bit-identical with zero phase-clock
  allocations; ``SKYLARK_PHASES=0`` keeps tracing hot but stamps no
  phases (the bench isolation knob).
- Distinct raw metric names that sanitize identically stay distinct on
  the wire (hash suffix), and per-tenant counters ride ONE family with
  a ``tenant`` label.
"""

import json
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

from libskylark_tpu import serve, telemetry
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.serve import server as server_mod
from libskylark_tpu.telemetry import timeline as timeline_mod
from libskylark_tpu.telemetry.fleet import merge_snapshots
from libskylark_tpu.telemetry.phases import PHASES

pytestmark = pytest.mark.trace

M, N = 64, 5
_rng = np.random.default_rng(777)
A = _rng.standard_normal((M, N))


@pytest.fixture
def traced(monkeypatch):
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    monkeypatch.delenv("SKYLARK_TRACE", raising=False)
    monkeypatch.delenv("SKYLARK_PHASES", raising=False)
    monkeypatch.delenv("SKYLARK_SLO", raising=False)
    telemetry.reset()
    telemetry.drain_traces()
    telemetry.reset_slo()
    telemetry.reset_timeline()
    server_mod._LATENCIES.clear()
    yield
    telemetry.reset()
    telemetry.drain_traces()
    telemetry.reset_slo()
    telemetry.reset_timeline()
    server_mod._LATENCIES.clear()


def _ls_server(**kw):
    params = serve.ServeParams(
        max_coalesce=kw.pop("max_coalesce", 4),
        max_queue=kw.pop("max_queue", 256),
        warm_start=False,
        prime=False,
        **kw,
    )
    srv = serve.Server(params, seed=7)
    srv.registry.register_system("sys", A, context=SketchContext(seed=3))
    return srv


def _fresh_req():
    return serve.make_request(
        "ls_solve", system="sys", b=_rng.standard_normal(M)
    )


# ---------------------------------------------------------------------------
# the phase clock


def test_phase_clock_sums_to_e2e(traced):
    srv = _ls_server().start()
    try:
        srv.call(_fresh_req())  # warm: the measured request won't compile
        r = srv.call(_fresh_req())
    finally:
        srv.stop()
    assert r["ok"]
    phases = r["trace"]["phases"]
    serve_phases = [p for p in PHASES if p != "collective_wait"]
    assert sorted(phases) == sorted(serve_phases)
    assert all(v >= 0 for v in phases.values()), phases
    e2e = r["trace"]["e2e_ms"]
    assert e2e > 0
    # THE acceptance contract: the decomposition tiles the wall
    assert abs(sum(phases.values()) - e2e) / e2e <= 0.10, (phases, e2e)
    # each phase also landed on its bucketed histogram
    hists = telemetry.REGISTRY.snapshot()["histograms"]
    for p in serve_phases:
        h = hists[f"phase.{p}_ms"]
        assert h["count"] >= 1
        assert "buckets" in h


def test_phases_gate_keeps_tracing_hot(traced, monkeypatch):
    monkeypatch.setenv("SKYLARK_PHASES", "0")
    srv = _ls_server().start()
    try:
        r = srv.call(_fresh_req())
    finally:
        srv.stop()
    assert r["ok"]
    assert r["trace"]["trace_id"]  # tracing still on
    assert "phases" not in r["trace"]
    hists = telemetry.REGISTRY.snapshot()["histograms"]
    assert not any(k.startswith("phase.") for k in hists)


def test_disabled_run_allocates_no_phase_state(monkeypatch):
    monkeypatch.setenv("SKYLARK_TELEMETRY", "0")
    telemetry.reset()
    server_mod._LATENCIES.clear()
    srv = _ls_server().start()
    try:
        r = srv.call(_fresh_req())
    finally:
        srv.stop()
    assert r["ok"]
    assert "trace_id" not in r["trace"]
    assert "phases" not in r["trace"]
    snap = telemetry.REGISTRY.snapshot()
    assert snap["counters"] == {} and snap["histograms"] == {}
    assert len(server_mod._LATENCIES) == 0
    assert telemetry.timeline_windows() == []


def test_cache_hit_carries_no_phases(traced):
    srv = _ls_server(cache=True).start()
    b = _rng.standard_normal(M)
    try:
        r1 = srv.call(serve.make_request("ls_solve", system="sys", b=b))
        r2 = srv.call(serve.make_request("ls_solve", system="sys", b=b))
    finally:
        srv.stop()
    assert "phases" in r1["trace"]
    assert r2["trace"].get("cache_hit") is True
    assert "phases" not in r2["trace"]


def test_observe_phase_registers_buckets(traced):
    telemetry.observe_phase("collective_wait", 3.0)
    h = telemetry.REGISTRY.snapshot()["histograms"][
        "phase.collective_wait_ms"
    ]
    assert h["count"] == 1
    assert h["buckets"]["count"] == 1
    assert sum(h["buckets"]["counts"]) == 1


# ---------------------------------------------------------------------------
# bucketed histograms in the registry


def test_enable_buckets_counts_and_inf_overflow(traced):
    telemetry.enable_buckets("t.ms", (1.0, 10.0, 100.0))
    for v in (0.5, 10.0, 50.0, 5000.0):
        telemetry.observe("t.ms", v)
    b = telemetry.REGISTRY.snapshot()["histograms"]["t.ms"]["buckets"]
    assert b["le"] == [1.0, 10.0, 100.0]
    # le semantics: 10.0 lands IN the le=10 bucket; 5000 overflows +Inf
    assert b["counts"] == [1, 1, 1, 1]
    assert b["count"] == 4
    assert b["sum"] == pytest.approx(5060.5)


def test_bucket_bounds_survive_reset(traced):
    telemetry.enable_buckets("t.ms", (1.0, 10.0))
    telemetry.observe("t.ms", 5.0)
    telemetry.reset()
    telemetry.observe("t.ms", 0.5)  # bounds are config, counts are data
    b = telemetry.REGISTRY.snapshot()["histograms"]["t.ms"]["buckets"]
    assert b["counts"] == [1, 0, 0] and b["count"] == 1


def test_bucket_quantile_upper_bound():
    le = [1.0, 10.0, 100.0]
    assert timeline_mod.bucket_quantile(le, [0, 0, 0, 0], 0.99) is None
    assert timeline_mod.bucket_quantile(le, [100, 0, 0, 0], 0.5) == 1.0
    assert timeline_mod.bucket_quantile(le, [50, 48, 2, 0], 0.99) == 100.0
    # overflow bucket reports the last finite bound
    assert timeline_mod.bucket_quantile(le, [0, 0, 0, 5], 0.99) == 100.0


# ---------------------------------------------------------------------------
# exposition: collisions, tenant labels, strict 0.0.4


def test_colliding_raw_names_stay_distinct(traced):
    telemetry.inc("col.a.b", 2)
    telemetry.inc("col.a_b", 3)
    text = telemetry.prometheus_text()
    rows = {
        line.split()[0]: line.split()[1]
        for line in text.splitlines()
        if line.startswith("skylark_col_a_b")
    }
    # both raws export, under DIFFERENT hash-suffixed names, and the
    # unsuffixed collision name is gone entirely
    assert len(rows) == 2
    assert "skylark_col_a_b_total" not in rows
    assert sorted(int(v) for v in rows.values()) == [2, 3]
    for name in rows:
        assert re.fullmatch(r"skylark_col_a_b_[0-9a-f]{6}_total", name)


def test_tenant_counters_export_as_labels(traced):
    telemetry.inc("serve.tenant.a-b.requests", 2)
    telemetry.inc("serve.tenant.a.b.requests", 3)
    text = telemetry.prometheus_text()
    assert 'skylark_serve_tenant_requests_total{tenant="a-b"} 2' in text
    assert 'skylark_serve_tenant_requests_total{tenant="a.b"} 3' in text
    assert (
        text.count("# TYPE skylark_serve_tenant_requests_total counter")
        == 1
    )


_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _strict_parse(text):
    """Strict Prometheus text-format 0.0.4 parse: returns
    ``(types, samples)`` or asserts with the offending line."""
    types: dict = {}
    sampled: set = set()
    samples: list = []
    for line in text.splitlines():
        assert line == line.rstrip(), repr(line)
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            assert parts[0] == "#" and parts[1] == "TYPE", line
            fam, kind = parts[2], parts[3]
            assert _NAME_RE.match(fam), line
            assert kind in (
                "counter", "gauge", "histogram", "summary", "untyped"
            ), line
            assert fam not in types, f"duplicate TYPE: {line}"
            assert fam not in sampled, f"TYPE after samples: {line}"
            types[fam] = kind
            continue
        toks = line.split()
        assert len(toks) == 2, line
        namelab, val = toks
        name, brace, labels = namelab.partition("{")
        assert _NAME_RE.match(name), line
        if brace:
            assert labels.endswith("}"), line
            labels = labels[:-1]
            for part in labels.split(","):
                m = re.fullmatch(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"',
                                 part)
                assert m, line
        float(val)  # must parse (inf/nan spellings included)
        # every sample belongs to a family whose TYPE line came first
        fam = name
        if fam not in types:
            for suffix in ("_bucket", "_count", "_sum"):
                if name.endswith(suffix):
                    fam = name[: -len(suffix)]
                    break
        assert fam in types, f"sample without TYPE: {line}"
        sampled.add(fam)
        samples.append((fam, name, labels if brace else "", float(val)))
    return types, samples


def _histogram_families_check(types, samples):
    checked = 0
    for fam, kind in types.items():
        if kind != "histogram":
            continue
        buckets = []
        count = total = None
        for f, name, labels, val in samples:
            if f != fam:
                continue
            if name == fam + "_bucket":
                m = re.search(r'le="([^"]+)"', labels)
                assert m, (fam, labels)
                buckets.append((float(m.group(1)), val))
            elif name == fam + "_count":
                count = val
            elif name == fam + "_sum":
                total = val
        assert buckets and count is not None and total is not None, fam
        les = [le for le, _ in buckets]
        assert les == sorted(les) and len(set(les)) == len(les), fam
        cum = [c for _, c in buckets]
        assert all(a <= b for a, b in zip(cum, cum[1:])), (fam, cum)
        assert les[-1] == float("inf"), fam
        assert cum[-1] == count, (fam, cum[-1], count)
        checked += 1
    return checked


@pytest.mark.serve
def test_metrics_strict_prometheus_004_under_traffic(traced, monkeypatch):
    monkeypatch.setenv("SKYLARK_SLO", "ls_solve:5000:99")
    srv = _ls_server().start()
    httpd = serve.serve_http(srv)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        failures = []
        stop = threading.Event()

        def scrape():
            while not stop.is_set():
                try:
                    with urllib.request.urlopen(
                        base + "/metrics", timeout=10
                    ) as resp:
                        _strict_parse(resp.read().decode())
                except Exception as e:  # noqa: BLE001 — collected
                    failures.append(repr(e))
                    return

        t = threading.Thread(target=scrape, daemon=True)
        t.start()
        results = [srv.call(_fresh_req()) for _ in range(12)]
        stop.set()
        t.join(timeout=10)
        assert not failures, failures
        assert all(r["ok"] for r in results)
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
    finally:
        httpd.shutdown()
        srv.stop()
    types, samples = _strict_parse(text)
    # at least the serve latency + phase histograms expose real buckets
    assert _histogram_families_check(types, samples) >= 2
    assert "skylark_serve_latency_ms_bucket" in text
    assert 'skylark_slo_budget_remaining{objective="ls_solve"}' in text


# ---------------------------------------------------------------------------
# the SLO engine


@pytest.mark.serve
def test_slo_burn_drill_lands_in_violations_ring(traced, monkeypatch):
    # an impossible threshold: every request breaches, the budget burns
    monkeypatch.setenv("SKYLARK_SLO", "ls_solve:0.0001:99")
    srv = _ls_server().start()
    httpd = serve.serve_http(srv)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        for _ in range(12):
            assert srv.call(_fresh_req())["ok"]
        with urllib.request.urlopen(base + "/slo", timeout=10) as resp:
            endpoint = json.loads(resp.read())
    finally:
        httpd.shutdown()
        srv.stop()

    report = telemetry.slo_report()["ls_solve"]
    assert report["bad"] == report["window"] >= 12
    assert report["budget_remaining"] < 0.25
    assert report["burning"] is True
    snap = telemetry.snapshot()
    assert snap["slo"]["burns"] == 1  # edge-triggered: ONE incident
    assert snap["slo"]["breaches"] >= 12
    assert snap["gauges"]["slo.budget_remaining.ls_solve"] < 0.25
    # the minted slo_burn violation is in the recorder's violations ring
    burns = [
        t for t in telemetry.trace_ids()["violations"]
        if t.startswith("slo-burn-")
    ]
    assert len(burns) == 1
    payload = telemetry.get_trace(burns[0])
    assert payload["op"] == "slo_burn" and payload["slo"] == "ls_solve"
    assert payload["budget_remaining"] < 0.25
    # the endpoint serves the same state
    assert endpoint["slo_spec"] == "ls_solve:0.0001:99"
    assert endpoint["objectives"]["ls_solve"]["burning"] is True


def test_slo_tenant_scoping_and_parse_errors(traced, monkeypatch):
    monkeypatch.setenv(
        "SKYLARK_SLO", "bogus,ls_solve@acme:0.0001:99,also:bad"
    )
    # default-tenant traffic never touches the acme-scoped objective
    telemetry.observe_slo("ls_solve", 100.0, tenant="default")
    assert telemetry.slo_report()["ls_solve@acme"]["window"] == 0
    telemetry.observe_slo("ls_solve", 100.0, tenant="acme")
    report = telemetry.slo_report()
    assert list(report) == ["ls_solve@acme"]  # malformed entries skipped
    assert report["ls_solve@acme"]["bad"] == 1
    counters = telemetry.REGISTRY.snapshot()["counters"]
    assert counters["slo.parse_errors"] >= 2


def test_slo_sheds_always_breach(traced, monkeypatch):
    monkeypatch.setenv("SKYLARK_SLO", "ls_solve:1000000:50")
    telemetry.observe_slo("ls_solve", 0.1)
    telemetry.observe_slo("ls_solve", 0.1, shed=True)
    report = telemetry.slo_report()["ls_solve"]
    assert report["window"] == 2 and report["bad"] == 1


# ---------------------------------------------------------------------------
# the shed-aware latency reservoir


def test_latency_reservoir_records_sheds(traced):
    server_mod.record_latency(1.0)
    server_mod.record_latency(2.0)
    server_mod.record_latency(500.0, shed=True)
    pct = server_mod.latency_percentiles()
    assert pct["latency_shed_samples"] == 1
    assert pct["latency_p99_ms"] > 400  # sheds dominate the full view
    assert pct["latency_p99_ms_served"] <= 2.0  # …and vanish from served


@pytest.mark.serve
def test_admission_shed_lands_in_reservoir(traced):
    srv = _ls_server(max_queue=1, max_coalesce=1)
    futures = [srv.submit(_fresh_req()) for _ in range(8)]
    srv.start()
    results = [f.result() for f in futures]
    srv.stop()
    sheds = [r for r in results if not r["ok"]]
    assert sheds and all(
        r["error"]["code"] == 112 for r in sheds
    )
    pct = server_mod.latency_percentiles()
    assert pct["latency_shed_samples"] == len(sheds)
    assert "latency_p50_ms_served" in pct


# ---------------------------------------------------------------------------
# the timeline ring


def test_timeline_windows_and_derived_series(traced, monkeypatch):
    assert telemetry.timeline_tick() is False  # first tick baselines
    telemetry.inc("serve.requests", 10)
    telemetry.inc("serve.cache.hit", 3)
    telemetry.inc("serve.cache.miss", 1)
    assert telemetry.timeline_tick(
        extra={"queue_depth": 7}, force=True
    ) is True
    (w,) = telemetry.timeline_windows()
    assert w["counters"]["serve.requests"] == 10
    assert w["dt_s"] >= 0
    assert w["derived"]["qps"] > 0
    assert w["derived"]["cache_hit_rate"] == 0.75
    assert w["derived"]["queue_depth"] == 7
    assert telemetry.REGISTRY.snapshot()["counters"]["timeline.ticks"] == 1

    # deltas, not totals: a quiet window shows zero requests
    assert telemetry.timeline_tick(force=True) is True
    w2 = telemetry.timeline_windows()[-1]
    assert "serve.requests" not in w2["counters"]

    # the ring is bounded by the capacity knob
    monkeypatch.setenv("SKYLARK_TIMELINE_CAPACITY", "2")
    for _ in range(4):
        telemetry.timeline_tick(force=True)
    assert len(telemetry.timeline_windows()) == 2


def test_timeline_interval_gates_lazy_ticks(traced, monkeypatch):
    monkeypatch.setenv("SKYLARK_TIMELINE_INTERVAL_S", "3600")
    telemetry.timeline_tick()  # baseline
    assert telemetry.timeline_tick() is False  # interval not elapsed
    monkeypatch.setenv("SKYLARK_TIMELINE_INTERVAL_S", "0.05")
    time.sleep(0.06)
    assert telemetry.timeline_tick() is True


@pytest.mark.serve
def test_timeline_endpoint_rolls_the_ring(traced, monkeypatch):
    monkeypatch.setenv("SKYLARK_TIMELINE_INTERVAL_S", "0.05")
    srv = _ls_server().start()
    httpd = serve.serve_http(srv)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        srv.call(_fresh_req())
        time.sleep(0.06)
        srv.call(_fresh_req())  # the worker loop ticks past the interval
        time.sleep(0.06)
        with urllib.request.urlopen(base + "/timeline", timeout=10) as r:
            state = json.loads(r.read())
    finally:
        httpd.shutdown()
        srv.stop()
    assert state["capacity"] == 120
    assert state["windows"], "scraping /timeline closes a window"
    assert "derived" in state["windows"][-1]


# ---------------------------------------------------------------------------
# fleet merge of bucketed histograms


def test_merge_snapshots_sums_matching_buckets():
    def snap(counts, count, total):
        return {
            "counters": {},
            "gauges": {},
            "histograms": {
                "h": {
                    "count": count, "sum": total, "min": 1.0, "max": 9.0,
                    "buckets": {"le": [1.0, 10.0], "counts": counts,
                                "count": count, "sum": total},
                }
            },
        }

    merged = merge_snapshots([snap([1, 2, 0], 3, 6.0),
                              snap([0, 1, 1], 2, 15.0)])
    b = merged["histograms"]["h"]["buckets"]
    assert b["counts"] == [1, 3, 1]
    assert b["count"] == 5 and b["sum"] == 21.0

    # mismatched bounds DROP the buckets instead of summing misaligned
    other = snap([4, 4], 8, 1.0)
    other["histograms"]["h"]["buckets"]["le"] = [5.0]
    merged = merge_snapshots([snap([1, 2, 0], 3, 6.0), other])
    assert "buckets" not in merged["histograms"]["h"]
    assert merged["histograms"]["h"]["count"] == 11  # moments still fold


# ---------------------------------------------------------------------------
# skylark-top: SLO panel + sparklines, degradation-safe


def test_top_slo_and_timeline_panels_render():
    from libskylark_tpu.cli import top

    assert top._slo_lines({"_error": "boom"}) == ["  slo: n/a"]
    assert top._slo_lines({"objectives": {}}) == []
    lines = top._slo_lines({"objectives": {
        "ls_solve": {"threshold_ms": 5.0, "target_pct": 99.0,
                     "window": 64, "bad": 3, "budget_remaining": -3.7,
                     "burning": True},
    }})
    assert any("BURNING" in ln for ln in lines)

    assert top._timeline_lines({"_error": "x"}) == ["  timeline: n/a"]
    assert top._timeline_lines({"windows": []}) == [
        "  timeline: (no windows yet)"
    ]
    lines = top._timeline_lines({
        "interval_s": 5.0,
        "windows": [
            {"derived": {"qps": float(q), "p99_ms": 1.0,
                         "queue_depth": 0, "cache_hit_rate": None}}
            for q in range(6)
        ],
    })
    assert any("qps" in ln and "▁" in ln for ln in lines)

    assert top._spark([]) == "n/a"
    assert top._spark([2, 2, 2]) == "▁▁▁"  # flat series, no div-by-zero


def test_top_survives_malformed_slo_and_timeline(monkeypatch):
    from libskylark_tpu.cli import top

    shapes = {
        "http://c/healthz": {"ok": True},
        "http://c/stats": {"counters": {}},
        "http://c/slo": {"objectives": "not-a-dict"},
        "http://c/timeline": {"windows": [17, "junk", {"derived": None}]},
    }
    monkeypatch.setattr(
        top, "_fetch_json",
        lambda url, timeout=2.0: shapes.get(url, {"_error": "boom"}),
    )
    args = type(
        "A", (), {"url": ["http://c"], "root": None, "telemetry_dir": None}
    )()
    status = {}
    frame = top.render_frame(args, status)
    assert status["answered"] == 1
    assert "serve http://c" in frame  # rendered, did not crash

    # an older replica: /slo and /timeline 404 into _error → n/a panels
    monkeypatch.setattr(
        top, "_fetch_json",
        lambda url, timeout=2.0: (
            {"ok": True} if url.endswith(("/healthz", "/stats"))
            else {"_error": "HTTP Error 404"}
        ),
    )
    frame = top.render_frame(args, {})
    assert "slo: n/a" in frame and "timeline: n/a" in frame


# ---------------------------------------------------------------------------
# static doc contracts


def _docs_text():
    import pathlib

    root = pathlib.Path(__file__).parent.parent
    return (root / "docs" / "observability.md").read_text(encoding="utf-8")


def test_every_phase_name_documented():
    docs = _docs_text()
    for phase in PHASES:
        assert f"`{phase}`" in docs, (
            f"phase {phase!r} has no row in docs/observability.md"
        )


def test_every_slo_and_timeline_counter_documented():
    import pathlib

    tel = pathlib.Path(__file__).parent.parent / "libskylark_tpu" / (
        "telemetry"
    )
    minted = set()
    for mod in ("slo.py", "timeline.py"):
        src = (tel / mod).read_text(encoding="utf-8")
        minted.update(
            re.findall(r'inc\("((?:slo|timeline)\.[a-z_]+)"', src)
        )
    assert minted >= {"slo.burns", "timeline.ticks"}, minted
    docs = _docs_text()
    missing = sorted(
        c for c in minted if f"`{c}`" not in docs and c not in docs
    )
    assert not missing, (
        f"counters minted but undocumented in docs/observability.md: "
        f"{missing}"
    )


def test_slo_and_timeline_knobs_documented():
    docs = _docs_text()
    for knob in (
        "SKYLARK_PHASES",
        "SKYLARK_SLO",
        "SKYLARK_SLO_WINDOW",
        "SKYLARK_SLO_BURN",
        "SKYLARK_TIMELINE_INTERVAL_S",
        "SKYLARK_TIMELINE_CAPACITY",
    ):
        assert knob in docs
