"""Telemetry-layer tests: registry, spans, JSONL ledger schema, and the
acceptance run of docs/observability.md — a guarded streaming least-
squares pass with an injected sketch fault, checked against its ledger.
"""

import json
import os

import numpy as np
import pytest

from libskylark_tpu import plans, telemetry
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.linalg import streaming_least_squares
from libskylark_tpu.resilient import FaultPlan
from libskylark_tpu.streaming import StreamParams

pytestmark = pytest.mark.telemetry

N, D, BATCH = 96, 6, 12  # 8 batches per pass


def _make_problem(rank_deficient=False):
    rng = np.random.default_rng(7)
    A = rng.standard_normal((N, D))
    if rank_deficient:
        # Duplicate column: S·A is numerically singular for any linear
        # sketch, so certify_sketch must return a RESKETCH verdict.
        A[:, -1] = A[:, 0]
    b = rng.standard_normal(N)
    return A, b


def _batches(A, b):
    def factory(start):
        def gen():
            for i in range(start, N // BATCH):
                sl = slice(i * BATCH, (i + 1) * BATCH)
                yield A[sl], b[sl]

        return gen()

    return factory


@pytest.fixture
def ledger_dir(tmp_path, monkeypatch):
    """Telemetry ON with a fresh ledger in tmp_path; fully unwound after."""
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.configure(str(tmp_path))
    telemetry.reset()
    plans.reset()
    yield tmp_path
    telemetry.close()
    telemetry.configure(None)
    telemetry.reset()


def _read_ledger():
    telemetry.flush()
    path = telemetry.ledger_path()
    assert path is not None, "no ledger file was opened"
    with open(path, encoding="utf-8") as fh:
        return [json.loads(line) for line in fh]


class TestRegistry:
    def test_counters_gauges_histograms(self, monkeypatch):
        monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
        telemetry.reset()
        try:
            telemetry.inc("a.calls")
            telemetry.inc("a.calls", 2)
            telemetry.set_gauge("g", 1.5)
            telemetry.observe("h", 2.0)
            telemetry.observe("h", 4.0)
            snap = telemetry.snapshot()
            assert snap["counters"]["a.calls"] == 3
            assert snap["gauges"]["g"] == 1.5
            h = snap["histograms"]["h"]
            assert (h["count"], h["sum"], h["min"], h["max"]) == (2, 6.0, 2.0, 4.0)
        finally:
            telemetry.reset()

    def test_disabled_path_is_inert(self, monkeypatch):
        monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
        telemetry.reset()
        try:
            telemetry.inc("a.calls")
            monkeypatch.setenv("SKYLARK_TELEMETRY", "0")
            telemetry.inc("a.calls")
            telemetry.set_gauge("g", 9)
            telemetry.observe("h", 9)
            assert telemetry.span("x") is telemetry.NOOP_SPAN
            assert telemetry.event("k", "n", {"a": 1}) is None
            assert telemetry.emit("k", "n", a=1) is None
            assert telemetry.run_summary("n", {"a": 1}) is None
            snap = telemetry.snapshot()
            assert snap["counters"]["a.calls"] == 1
            assert "g" not in snap["gauges"] and "h" not in snap["histograms"]
        finally:
            telemetry.reset()

    def test_report_reuses_timer_table(self, monkeypatch):
        monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
        telemetry.reset()
        try:
            telemetry.inc("x.count", 5)
            telemetry.set_gauge("rate", 2.5)
            rep = telemetry.report()
            assert "x.count" in rep and "gauge.rate" in rep
            # Single-process distributed path reduces over 1 rank.
            rep_d = telemetry.report(distributed=True)
            assert "over 1 process" in rep_d
        finally:
            telemetry.reset()


class TestLedger:
    def test_span_nesting_and_schema(self, ledger_dir):
        with telemetry.span("outer", stage="t"):
            with telemetry.span("inner") as si:
                si.attrs["late"] = 1
        events = _read_ledger()
        for ev in events:
            assert set(ev) == {"ts", "seq", "pid", "kind", "name", "attrs"}
            assert ev["pid"] == os.getpid()
        seqs = [ev["seq"] for ev in events]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        assert [(ev["kind"], ev["name"]) for ev in events] == [
            ("span_start", "outer"),
            ("span_start", "inner"),
            ("span_end", "inner"),
            ("span_end", "outer"),
        ]
        outer_start, inner_start, inner_end, _ = events
        assert inner_start["attrs"]["parent"] == outer_start["seq"]
        assert inner_start["attrs"]["depth"] == 1
        assert inner_end["attrs"]["late"] == 1  # amended inside the region
        assert inner_end["attrs"]["span"] == inner_start["seq"]
        assert inner_end["attrs"]["seconds"] >= 0
        snap = telemetry.snapshot()
        assert snap["counters"]["span.outer.calls"] == 1
        assert snap["counters"]["span.inner.calls"] == 1

    def test_numpy_attrs_coerce_to_json(self, ledger_dir):
        telemetry.emit(
            "probe", "coerce",
            i=np.int64(3), f=np.float32(1.5), a=np.arange(2),
        )
        (ev,) = _read_ledger()
        assert ev["attrs"] == {"i": 3, "f": 1.5, "a": [0, 1]}

    def test_no_directory_means_no_file(self, monkeypatch):
        monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
        monkeypatch.delenv("SKYLARK_TELEMETRY_DIR", raising=False)
        telemetry.configure(None)
        telemetry.reset()
        try:
            seq1 = telemetry.emit("probe", "nofile", k=1)
            seq2 = telemetry.emit("probe", "nofile", k=2)
            # Events still sequence (registry/summary keep working) but
            # nothing opens on disk.
            assert seq1 is not None and seq2 == seq1 + 1
            assert telemetry.ledger_path() is None
        finally:
            telemetry.reset()


@pytest.mark.streaming
@pytest.mark.guard
class TestAcceptance:
    """The ISSUE acceptance run: SKYLARK_TELEMETRY=1, streaming guarded
    least squares, one injected ``bad_sketch_at`` fault, rank-deficient A
    (so certification fails with a RESKETCH verdict)."""

    def _run(self):
        A, b = _make_problem(rank_deficient=True)
        return streaming_least_squares(
            _batches(A, b), N, D, SketchContext(seed=3),
            stream_params=StreamParams(),
            fault_plan=FaultPlan(bad_sketch_at=1),
        )

    def test_ledger_records_the_run(self, ledger_dir, monkeypatch):
        monkeypatch.setenv("SKYLARK_GUARD", "1")
        x, info = self._run()
        events = _read_ledger()
        kinds = {(e["kind"], e["name"]) for e in events}

        # Chunk spans from the streaming engine.
        assert ("span_start", "stream.chunk") in kinds
        assert ("span_end", "stream.chunk") in kinds
        chunk_ends = [
            e for e in events
            if e["kind"] == "span_end" and e["name"] == "stream.chunk"
        ]
        assert all("rows" in e["attrs"] for e in chunk_ends)

        # The Inf-poisoned batch tripped the sentinel and was replayed.
        replays = [
            e for e in events if e["kind"] == "guard" and e["name"] == "replay"
        ]
        assert len(replays) == 1

        # Certification of the rank-deficient stream: RESKETCH verdict on
        # the initial rung, then the SVD small-solve fallback.
        initial = [
            e for e in events if e["kind"] == "guard" and e["name"] == "initial"
        ]
        assert initial and initial[-1]["attrs"]["verdict"] == "RESKETCH"
        assert any(
            e["kind"] == "guard" and e["name"] == "fallback" for e in events
        )

        # Terminal run_summary: last word of the ledger, carrying the
        # run's info dict and the registry + plan-cache snapshot.
        summaries = [e for e in events if e["kind"] == "run_summary"]
        assert len(summaries) == 1 and summaries[0]["name"] == "streaming_lsq"
        assert summaries[0]["seq"] == max(e["seq"] for e in events)
        payload = summaries[0]["attrs"]
        assert set(payload["info"]) == set(info)
        assert payload["info"]["recovery"] == info["recovery"]
        assert payload["info"]["rows"] == N
        # Counters in the summary snapshot match plans.stats(): nothing
        # touched the plan cache after the terminal event.
        assert payload["snapshot"]["plans"] == plans.stats()
        # The replay registered in the counter groups too.
        assert payload["snapshot"]["guard"].get("replay") == 1
        assert payload["snapshot"]["counters"]["stream.replays"] == 1

    def test_disabled_run_is_bit_identical(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SKYLARK_GUARD", "1")
        monkeypatch.delenv("SKYLARK_TELEMETRY", raising=False)
        telemetry.close()
        x_off, info_off = self._run()
        monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
        telemetry.configure(str(tmp_path))
        telemetry.reset()
        plans.reset()
        try:
            x_on, info_on = self._run()
        finally:
            telemetry.close()
            telemetry.configure(None)
            telemetry.reset()
        np.testing.assert_array_equal(np.asarray(x_off), np.asarray(x_on))
        assert info_off["recovery"] == info_on["recovery"]
        assert info_off["rows"] == info_on["rows"]
        assert info_off["batches"] == info_on["batches"]
