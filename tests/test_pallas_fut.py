"""Pallas fused RFUT kernel vs XLA path (interpret mode on CPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import SketchContext
from libskylark_tpu.sketch import FJLT, wht
from libskylark_tpu.sketch import pallas_fut


class TestPallasRFUT:
    @pytest.mark.slow
    @pytest.mark.parametrize("n,nb", [(4096, 4096), (200, 256), (2048, 2048)])
    def test_matches_xla_wht(self, rng, n, nb):
        m = 16
        x = jnp.asarray(rng.standard_normal((m, n)).astype(np.float32))
        d = jnp.asarray(np.sign(rng.standard_normal(n)).astype(np.float32))
        out = pallas_fut.rfut_rowwise(x, d, nb, interpret=True)
        xp = jnp.pad(x * d[None, :], ((0, 0), (0, nb - n)))
        ref = wht(xp, axis=1)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_supported_predicate(self):
        assert pallas_fut.supported(1024, 4096, 4096)
        assert not pallas_fut.supported(7, 4096, 4096)  # rows not tileable
        assert not pallas_fut.supported(64, 100, 100)  # not pow2
        assert not pallas_fut.supported(64, 128, 128)  # below 2*F2
        assert not pallas_fut.supported(64, 1 << 18, 1 << 18)  # too large

    def test_fjlt_pallas_path_matches_xla(self, rng):
        n, s, m = 512, 64, 32
        A = jnp.asarray(rng.standard_normal((m, n)).astype(np.float32))
        S1 = FJLT(n, s, SketchContext(seed=3))
        ref = S1.apply(A, "rowwise")  # XLA path (CPU backend)
        out = S1._apply_pallas(A, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    @pytest.mark.parametrize("dim,shape", [
        ("rowwise", (32, 512)), ("columnwise", (512, 32)),
    ])
    def test_fjlt_real_dispatch_via_interpret(self, rng, monkeypatch, dim, shape):
        # Exercise apply()'s ACTUAL Pallas branch conditions (not a
        # hand-copied dispatch): force the gate open and run the kernel in
        # interpret mode on CPU.
        import libskylark_tpu.sketch.fjlt as fjlt_mod

        n, s = 512, 64
        A = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
        S1 = FJLT(n, s, SketchContext(seed=4))
        ref = S1.apply(A, dim)  # XLA path (gate closed on CPU)
        monkeypatch.setattr(fjlt_mod, "_use_pallas", lambda: True)
        orig = S1._apply_pallas
        monkeypatch.setattr(
            FJLT, "_apply_pallas",
            lambda self, B, interpret=False: orig(B, interpret=True),
        )
        out = S1.apply(A, dim)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )


class TestPallasSampledFJLT:
    """The fused sampled-epilogue kernel (VERDICT r4 item 5): selection +
    rescale inside the kernel, only (m, S) ever written to HBM."""

    def test_sampled_matches_base_plus_take(self, rng):
        m, nb, s = 32, 512, 128
        x = jnp.asarray(rng.standard_normal((m, nb)).astype(np.float32))
        d = jnp.asarray(np.sign(rng.standard_normal(nb)).astype(np.float32))
        idx = rng.integers(0, nb, s).astype(np.int32)  # with duplicates
        out = pallas_fut.rfut_rowwise_sampled(x, d, nb, idx, interpret=True)
        base = pallas_fut.rfut_rowwise(x, d, nb, interpret=True)
        ref = np.asarray(base)[:, idx] * np.sqrt(nb / s)
        np.testing.assert_allclose(
            np.asarray(out), ref, rtol=1e-5, atol=1e-5
        )

    @pytest.mark.slow
    def test_fjlt_fused_path_matches_xla(self, rng):
        n, s, m = 512, 128, 32
        A = jnp.asarray(rng.standard_normal((m, n)).astype(np.float32))
        S1 = FJLT(n, s, SketchContext(seed=5))
        ref = S1.apply(A, "rowwise")  # XLA path (CPU backend)
        # interpret=True takes the fused branch (supported_sampled holds
        # for s=128) without needing the hardware probe.
        out = S1._apply_pallas(A, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    def test_supported_sampled_predicate(self):
        assert pallas_fut.supported_sampled(1024, 4096, 4096, 1024)
        assert not pallas_fut.supported_sampled(1024, 4096, 4096, 64)
        assert not pallas_fut.supported_sampled(1024, 4096, 4096, 200)
        assert not pallas_fut.supported_sampled(7, 4096, 4096, 256)

    def test_unsupported_shape_raises_value_error(self, rng):
        """A shape the gate rejects must fail with a pointer to the
        predicate, not an opaque TypeError from `m // None`."""
        m, nb, s = 7, 512, 128  # no tile divides m=7
        assert pallas_fut._tile_rows(m, nb) is None
        x = jnp.asarray(rng.standard_normal((m, nb)).astype(np.float32))
        d = jnp.asarray(np.sign(rng.standard_normal(nb)).astype(np.float32))
        idx = rng.integers(0, nb, s).astype(np.int32)
        with pytest.raises(ValueError, match="check supported_sampled"):
            pallas_fut.rfut_rowwise_sampled(x, d, nb, idx, interpret=True)
        with pytest.raises(ValueError, match="check supported"):
            pallas_fut.rfut_rowwise(x, d, nb, interpret=True)

    def test_fused_disable_env(self, rng, monkeypatch):
        n, s, m = 512, 128, 16
        monkeypatch.setenv("SKYLARK_PALLAS_FJLT_SAMPLED", "0")
        A = jnp.asarray(rng.standard_normal((m, n)).astype(np.float32))
        S1 = FJLT(n, s, SketchContext(seed=6))
        ref = S1.apply(A, "rowwise")
        out = S1._apply_pallas(A, interpret=True)  # forced two-step
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )
