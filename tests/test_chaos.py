"""Host-level chaos + repartition-on-resume tests (single-process tier).

Every multi-host failure mode that can be simulated inside one process
is exercised here: the repartition planner's range/assignment
arithmetic, shrink (4→2) and grow (2→4) kill-and-resume through
``replan_resume`` + ``execute_rank_plan``, the driver-level repartition
resume (including ``info["replay"]`` accounting and the strict-policy
code-109 guarantee), collective watchdog deadlines (code 110),
stale-epoch fencing (code 111), checkpoint-slot epoch rejection, and
the :class:`HostFaultPlan` chaos knobs themselves.  REAL multi-process
chaos (rank SIGKILL, stragglers over a live ``jax.distributed`` world)
lives in ``tests/test_distributed.py`` (slow tier).

Bitwise assertions here are deliberate, not optimistic: the matrices
are integer-valued and the sketches are CWT (±1 hash values), so every
partial sum is exact integer arithmetic in float64 — associativity
holds bitwise, and a repartitioned resume (different summation
grouping!) must reproduce the uninterrupted run EXACTLY.
"""

import json
import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import sketch as sk
from libskylark_tpu import streaming
from libskylark_tpu.core import SketchContext
from libskylark_tpu.parallel import CollectiveWatchdog
from libskylark_tpu.plans import accumulate_slice
from libskylark_tpu.resilient import (
    FaultPlan,
    HostFaultPlan,
    SimulatedPreemption,
    corrupt_checkpoint,
    corrupt_manifest,
    tear_ledger_tail,
)
from libskylark_tpu.sketch.base import Dimension
from libskylark_tpu.streaming import (
    ElasticParams,
    RowPartition,
    elastic_run_stream,
    host_dir,
    read_epoch,
    read_progress,
    replan_resume,
    skip_batches,
)
from libskylark_tpu.streaming.elastic import MANIFEST_NAME, PROGRESS_NAME
from libskylark_tpu.streaming.repartition import (
    PlanRef,
    _assign,
    complement_ranges,
    load_plan,
    merge_ranges,
    scan_coverage,
)
from libskylark_tpu.utils.checkpoint import CheckpointStore
from libskylark_tpu.utils.exceptions import (
    CollectiveTimeoutError,
    InvalidParameters,
    StaleEpochError,
    WorldMismatchError,
)

pytestmark = pytest.mark.chaos

N, M, S_OUT = 60, 5, 16
BATCH = 7  # 60/7 -> 9 batches, last one ragged (4 rows)
KIND = "distributed_streaming_sketch"


def int_matrix(rng, n=N, m=M):
    """Integer-valued float64: with a CWT sketch (±1 values) every fold
    is exact, so bitwise identity survives ANY summation regrouping."""
    return jnp.asarray(rng.integers(-9, 10, size=(n, m)).astype(np.float64))


def blocks_of(*arrays, batch=BATCH):
    n = arrays[0].shape[0]
    out = []
    for lo in range(0, n, batch):
        sl = tuple(a[lo : lo + batch] for a in arrays)
        out.append(sl[0] if len(arrays) == 1 else sl)
    return out


def factory_of(*arrays, batch=BATCH):
    def factory(start):
        it = iter(blocks_of(*arrays, batch=batch))
        return skip_batches(it, start) if start else it

    return factory


def make_cwt(seed=31):
    return sk.CWT(N, S_OUT, SketchContext(seed=seed))


def rank_fold(A, S, part, rank, root, *, fault_plan=None,
              checkpoint_every=1):
    """One simulated rank's elastic fold into the shared root (the
    ``test_elastic.py`` idiom, CWT-exact here)."""
    r0, _ = part.row_range(rank)
    init = {
        "sa": jnp.zeros((S.s, M), jnp.float64),
        "row": np.asarray(r0, np.int64),
    }

    def step(acc, block, index):
        row = int(acc["row"])
        return {
            "sa": accumulate_slice(S, acc["sa"], block, row),
            "row": np.asarray(row + block.shape[0], np.int64),
        }

    params = ElasticParams(
        rank=rank, world_size=part.world_size, checkpoint_dir=str(root),
        checkpoint_every=checkpoint_every, prefetch=0,
    )
    return elastic_run_stream(
        factory_of(A), step, init, part, params, kind=KIND,
        fault_plan=fault_plan,
    )


def execute_all_ranks(plan, A, S, root, *, fault_plans=None):
    """Run every rank's share of ``plan`` in-process and sum the
    partials (the psum a real world would do)."""
    world = plan.partition.world_size

    def init_at(row0):
        return {
            "sa": jnp.zeros((S.s, M), jnp.float64),
            "row": np.asarray(row0, np.int64),
        }

    def step(acc, block, index):
        row = int(acc["row"])
        return {
            "sa": accumulate_slice(S, acc["sa"], block, row),
            "row": np.asarray(row + block.shape[0], np.int64),
        }

    total, info = None, None
    for rank in range(world):
        params = ElasticParams(
            rank=rank, world_size=world, checkpoint_dir=str(root),
            checkpoint_every=1, prefetch=0,
        )
        partial, info = streaming.execute_rank_plan(
            plan, factory_of(A), params=params, root=str(root),
            init_at=init_at, step_fn=step, kind=KIND,
            fault_plan=(fault_plans or {}).get(rank),
        )
        total = (
            partial["sa"]
            if total is None
            else total + np.asarray(partial["sa"])
        )
    return np.asarray(total), info


# ---------------------------------------------------------------------------
# Plan arithmetic
# ---------------------------------------------------------------------------


class TestPlanArithmetic:
    def test_merge_ranges_coalesces(self):
        assert merge_ranges([(3, 5), (0, 2), (1, 3), (7, 7)]) == [(0, 5)]
        assert merge_ranges([]) == []
        assert merge_ranges([(2, 4), (6, 8)]) == [(2, 4), (6, 8)]

    def test_complement_ranges(self):
        assert complement_ranges([(2, 4), (6, 8)], 9) == [
            (0, 2), (4, 6), (8, 9)
        ]
        assert complement_ranges([], 3) == [(0, 3)]
        assert complement_ranges([(0, 3)], 3) == []

    def test_assign_partitions_the_residual_exactly(self):
        refs = [
            PlanRef(directory=f"host-0000{i}/ckpt", step=2, start=2 * i,
                    end=2 * i + 2, epoch=0)
            for i in range(3)
        ]
        residual = [(6, 13)]
        for world in (1, 2, 4):
            a = _assign(refs, residual, world)
            b = _assign(refs, residual, world)
            # deterministic: same inputs, same plan — this is what lets
            # every rank derive the plan without communication
            assert {r: x.to_json() for r, x in a.items()} == {
                r: x.to_json() for r, x in b.items()
            }
            got_refs = sorted(
                (r.start, r.end) for x in a.values() for r in x.refs
            )
            assert got_refs == [(0, 2), (2, 4), (4, 6)]
            segs = merge_ranges(
                s for x in a.values() for s in x.segments
            )
            assert segs == [(6, 13)]
            # quota-balanced: no rank re-folds more than ceil(total/world)
            quota = -(-7 // world)
            for x in a.values():
                assert sum(e - s for s, e in x.segments) <= quota


# ---------------------------------------------------------------------------
# Repartitioned resumes: shrink, grow, corrupt hosts — all bitwise
# ---------------------------------------------------------------------------


class TestRepartitionResume:
    def test_shrink_4_to_2_bitwise(self, rng, tmp_path):
        A = int_matrix(rng)
        S = make_cwt()
        part4 = RowPartition(nrows=N, batch_rows=BATCH, world_size=4)
        # ranks 0, 1 finish; rank 2 dies after ONE durable batch; rank 3
        # never starts (dead host, no directory at all)
        rank_fold(A, S, part4, 0, tmp_path)
        rank_fold(A, S, part4, 1, tmp_path)
        with pytest.raises(SimulatedPreemption):
            rank_fold(A, S, part4, 2, tmp_path,
                      fault_plan=FaultPlan(preempt_after_chunk=0))

        part2 = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        plan = replan_resume(tmp_path, part2, kind=KIND)
        # world-4 split of 9 batches: [0,3) [3,5) [5,7) [7,9); rank 2
        # committed 1 of its 2 batches, rank 3 contributed nothing
        assert plan.completed == [(0, 6)]
        assert plan.residual == [(6, 9)]
        total, info = execute_all_ranks(plan, A, S, tmp_path)
        want = np.asarray(S.apply(A, Dimension.COLUMNWISE))
        assert np.array_equal(total, want)
        assert info["replayed"] == [[6, 9]]
        assert info["replayed_batches"] == 3
        assert info["from_world"] == 4 and info["to_world"] == 2
        # the epoch marker now fences the old world out
        assert read_epoch(tmp_path)["epoch"] == 1

    def test_grow_2_to_4_bitwise(self, rng, tmp_path):
        A = int_matrix(rng)
        S = make_cwt()
        part2 = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        rank_fold(A, S, part2, 0, tmp_path)  # [0, 5) complete
        with pytest.raises(SimulatedPreemption):  # [5, 6) durable of [5, 9)
            rank_fold(A, S, part2, 1, tmp_path,
                      fault_plan=FaultPlan(preempt_after_chunk=0))

        part4 = RowPartition(nrows=N, batch_rows=BATCH, world_size=4)
        plan = replan_resume(tmp_path, part4, kind=KIND)
        assert plan.completed == [(0, 6)]
        assert plan.residual == [(6, 9)]
        total, info = execute_all_ranks(plan, A, S, tmp_path)
        want = np.asarray(S.apply(A, Dimension.COLUMNWISE))
        assert np.array_equal(total, want)
        assert info["replayed"] == [[6, 9]]
        assert info["to_world"] == 4

    def test_corrupt_manifest_host_is_dropped_and_refolded(self, rng,
                                                           tmp_path):
        A = int_matrix(rng)
        S = make_cwt()
        part2 = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        rank_fold(A, S, part2, 0, tmp_path)
        rank_fold(A, S, part2, 1, tmp_path)  # finishes... then goes hostile
        corrupt_manifest(host_dir(tmp_path, 1))

        scan = scan_coverage(tmp_path, kind=KIND)
        assert scan["lost_hosts"] == [1]
        part1 = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        plan = replan_resume(tmp_path, part1, kind=KIND)
        # the hostile host's WHOLE range re-folds: its stores are not
        # trusted even though they exist
        assert plan.completed == [(0, 5)]
        assert plan.residual == [(5, 9)]
        assert plan.lost_hosts == [1]
        total, _ = execute_all_ranks(plan, A, S, tmp_path)
        assert np.array_equal(
            total, np.asarray(S.apply(A, Dimension.COLUMNWISE))
        )

    def test_plan_persists_and_reloads_identically(self, rng, tmp_path):
        A = int_matrix(rng)
        S = make_cwt()
        part2 = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        rank_fold(A, S, part2, 0, tmp_path)
        part1 = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        plan = replan_resume(tmp_path, part1, kind=KIND)
        again = load_plan(tmp_path, plan.epoch)
        assert again is not None
        assert again.signature() == plan.signature()
        assert again.to_json() == plan.to_json()

    def test_nrows_change_is_not_a_repartition(self, rng, tmp_path):
        # Coverage beyond the new partition's batch count means the
        # PROBLEM changed, not just the world — typed 109, not garbage.
        A = int_matrix(rng)
        S = make_cwt()
        part2 = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        rank_fold(A, S, part2, 0, tmp_path)
        rank_fold(A, S, part2, 1, tmp_path)
        smaller = RowPartition(nrows=N - 2 * BATCH, batch_rows=BATCH,
                               world_size=2)
        with pytest.raises(WorldMismatchError):
            replan_resume(tmp_path, smaller, kind=KIND)


# ---------------------------------------------------------------------------
# Driver-level repartition: the user-facing resume path
# ---------------------------------------------------------------------------


class TestDriverRepartition:
    def _seed_world2(self, rng, tmp_path):
        """World-2 run with rank 1 killed after one durable batch."""
        A = int_matrix(rng)
        S = make_cwt()
        part2 = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        rank_fold(A, S, part2, 0, tmp_path)
        with pytest.raises(SimulatedPreemption):
            rank_fold(A, S, part2, 1, tmp_path,
                      fault_plan=FaultPlan(preempt_after_chunk=0))
        return A, S

    def test_shrink_to_world_1_matches_uninterrupted_bitwise(self, rng,
                                                             tmp_path):
        A, S = self._seed_world2(rng, tmp_path)
        part1 = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        params = ElasticParams(
            resume=True, resume_policy="repartition",
            checkpoint_dir=str(tmp_path), checkpoint_every=1, prefetch=0,
        )
        got = streaming.sketch(
            factory_of(A), S, "columnwise", ncols=M, partition=part1,
            params=params,
        )
        want = streaming.sketch(factory_of(A), S, "columnwise", ncols=M)
        assert np.array_equal(np.asarray(got), np.asarray(want))

    def test_resume_is_idempotent(self, rng, tmp_path):
        # A second resume against the already-recovered root re-executes
        # the persisted plan (segment stores are complete, so nothing
        # re-folds) and lands on the identical bits.
        A, S = self._seed_world2(rng, tmp_path)
        part1 = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        params = ElasticParams(
            resume=True, resume_policy="repartition",
            checkpoint_dir=str(tmp_path), checkpoint_every=1, prefetch=0,
        )
        first = streaming.sketch(
            factory_of(A), S, "columnwise", ncols=M, partition=part1,
            params=params,
        )
        second = streaming.sketch(
            factory_of(A), S, "columnwise", ncols=M, partition=part1,
            params=params,
        )
        assert np.array_equal(np.asarray(first), np.asarray(second))
        assert read_epoch(tmp_path)["epoch"] == 1  # no epoch churn

    def test_strict_policy_preserves_code_109(self, rng, tmp_path):
        # The acceptance lock: --resume-policy strict keeps today's
        # fail-fast behavior bit-for-bit — a world change is code 109.
        A, S = self._seed_world2(rng, tmp_path)
        part1 = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        params = ElasticParams(
            resume=True, resume_policy="strict",
            checkpoint_dir=str(tmp_path), checkpoint_every=1, prefetch=0,
        )
        with pytest.raises(WorldMismatchError) as ei:
            streaming.sketch(
                factory_of(A), S, "columnwise", ncols=M, partition=part1,
                params=params,
            )
        assert ei.value.code == 109

    def test_least_squares_reports_replay(self, rng, tmp_path):
        A = int_matrix(rng)
        b = jnp.asarray(
            rng.integers(-9, 10, size=(N, 1)).astype(np.float64)
        )
        S = make_cwt()
        part2 = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        lsq_kind = "distributed_streaming_lsq"

        def fold(rank, fault_plan=None):
            r0, _ = part2.row_range(rank)
            init = {
                "sa": jnp.zeros((S.s, M), jnp.float64),
                "sb": jnp.zeros((S.s, 1), jnp.float64),
                "row": np.asarray(r0, np.int64),
            }

            def step(acc, block, index):
                ab, bb = block
                row = int(acc["row"])
                return {
                    "sa": accumulate_slice(S, acc["sa"], ab, row),
                    "sb": accumulate_slice(S, acc["sb"], bb, row),
                    "row": np.asarray(row + ab.shape[0], np.int64),
                }

            params = ElasticParams(
                rank=rank, world_size=2, checkpoint_dir=str(tmp_path),
                checkpoint_every=1, prefetch=0,
            )
            return elastic_run_stream(
                factory_of(A, b), step, init, part2, params,
                kind=lsq_kind, fault_plan=fault_plan,
            )

        fold(0)
        with pytest.raises(SimulatedPreemption):
            fold(1, fault_plan=FaultPlan(preempt_after_chunk=0))

        part1 = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        params = ElasticParams(
            resume=True, resume_policy="repartition",
            checkpoint_dir=str(tmp_path), checkpoint_every=1, prefetch=0,
        )
        x, info = streaming.sketch_least_squares(
            factory_of(A, b), S, ncols=M, partition=part1, params=params,
        )
        # only the dead rank's unledgered batches replay: rank 1 owned
        # [5, 9) and committed [5, 6)
        assert info["replay"]["replayed"] == [[6, 9]]
        assert info["replay"]["completed_batches"] == 6
        x2, info2 = streaming.sketch_least_squares(
            factory_of(A, b), S, ncols=M,
        )
        assert np.array_equal(np.asarray(x), np.asarray(x2))
        assert "replay" not in info2 or info2.get("replay") is None

    def test_bogus_policy_rejected(self):
        with pytest.raises(InvalidParameters):
            ElasticParams(resume_policy="optimistic")


# ---------------------------------------------------------------------------
# Collective watchdog: deadline, stragglers, stale peers
# ---------------------------------------------------------------------------


class TestCollectiveWatchdog:
    def test_timeout_names_stragglers(self, tmp_path):
        wd = CollectiveWatchdog(tmp_path, rank=0, world=3, epoch=0,
                                deadline_s=0.4, poll_s=0.05)
        # peer 1 arrives at the phase; peer 2 never does
        CollectiveWatchdog(tmp_path, rank=1, world=3).beat("psum")
        with pytest.raises(CollectiveTimeoutError) as ei:
            wd.guard("psum", lambda: time.sleep(30))
        assert ei.value.code == 110
        assert ei.value.phase == "psum"
        assert ei.value.stragglers == [2]

    def test_fast_collective_passes_through(self, tmp_path):
        wd = CollectiveWatchdog(tmp_path, rank=0, world=2, epoch=0,
                                deadline_s=5.0, poll_s=0.05)
        assert wd.guard("psum", lambda: 41 + 1) == 42

    def test_no_deadline_runs_inline(self, tmp_path):
        wd = CollectiveWatchdog(tmp_path, rank=0, world=2, epoch=0)
        assert wd.deadline_s is None
        assert wd.guard("psum", lambda: "inline") == "inline"

    def test_env_var_sets_deadline(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SKYLARK_COLLECTIVE_TIMEOUT_S", "0.25")
        wd = CollectiveWatchdog(tmp_path, rank=0, world=2)
        assert wd.deadline_s == 0.25

    def test_worker_exception_propagates(self, tmp_path):
        wd = CollectiveWatchdog(tmp_path, rank=0, world=1, epoch=0,
                                deadline_s=5.0, poll_s=0.05)

        def boom():
            raise ValueError("collective blew up")

        with pytest.raises(ValueError, match="blew up"):
            wd.guard("psum", boom)

    def test_stale_peer_epoch_fences_immediately(self, tmp_path):
        # A peer heartbeat from a HIGHER epoch means the world moved on:
        # code 111 right away, not a wasted deadline wait.
        CollectiveWatchdog(tmp_path, rank=1, world=2, epoch=3).beat("psum")
        wd = CollectiveWatchdog(tmp_path, rank=0, world=2, epoch=0,
                                deadline_s=30.0, poll_s=0.05)
        with pytest.raises(StaleEpochError) as ei:
            wd.guard("psum", lambda: time.sleep(30))
        assert ei.value.code == 111


# ---------------------------------------------------------------------------
# Epoch fencing + checkpoint-slot epoch rejection
# ---------------------------------------------------------------------------


class TestEpochFencing:
    def test_stale_writer_is_fenced_mid_stream(self, rng, tmp_path):
        # HostFaultPlan bumps the root epoch marker mid-fold (the rest
        # of the world repartitioned); this host's very next ledger
        # record must die with code 111, before any commit.
        A = int_matrix(rng)
        S = make_cwt()
        part1 = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        with pytest.raises(StaleEpochError) as ei:
            rank_fold(A, S, part1, 0, tmp_path,
                      fault_plan=HostFaultPlan(bump_epoch_at=2))
        assert ei.value.code == 111
        # batches 0 and 1 were ledgered before the fence tripped
        recs = read_progress(
            os.path.join(host_dir(tmp_path, 0), PROGRESS_NAME)
        )
        batches = [r["attrs"]["batch"] for r in recs
                   if r["attrs"].get("batch") is not None]
        assert batches == [0, 1]

    def test_store_rejects_slot_from_other_epoch(self, tmp_path):
        store = CheckpointStore(tmp_path)
        state = {"x": np.arange(4.0)}
        store.save(state, step=1, metadata={"elastic": {"epoch": 0}})
        with pytest.raises(StaleEpochError) as ei:
            store.load_latest(expect_epoch=1)
        assert ei.value.code == 111
        # ... while the matching epoch loads normally
        got, meta, step = store.load_latest(
            like={"x": np.zeros(4)}, expect_epoch=0
        )
        assert step == 1 and np.array_equal(got["x"], state["x"])

    def test_corrupt_newest_slot_still_falls_back(self, tmp_path):
        # The epoch check must not break the corrupt-slot fallback: a
        # corrupt NEWEST slot is skipped (CheckpointError internally),
        # and the epoch gate applies to the slot actually loaded.
        store = CheckpointStore(tmp_path, keep_last=3)
        store.save({"x": np.arange(4.0)}, step=1,
                   metadata={"elastic": {"epoch": 1}})
        newest = store.save({"x": np.arange(4.0) * 2}, step=2,
                            metadata={"elastic": {"epoch": 1}})
        corrupt_checkpoint(newest)
        got, meta, step = store.load_latest(expect_epoch=1)
        assert step == 1
        with pytest.raises(StaleEpochError):
            store.load_latest(expect_epoch=2)


# ---------------------------------------------------------------------------
# HostFaultPlan knobs (the in-process halves; SIGKILL is exercised in
# the multi-process tier)
# ---------------------------------------------------------------------------


class TestHostFaultPlan:
    def test_slow_rank_sleeps_once(self):
        naps = []
        hp = HostFaultPlan(slow_at_batch=1, slow_seconds=2.5,
                           sleep=naps.append)
        hp.before_batch(0)
        assert naps == []
        hp.before_batch(1)
        hp.before_batch(1)  # one-shot: a guard replay doesn't re-sleep
        assert naps == [2.5]

    def test_corrupt_manifest_at_fires_on_bound_host(self, tmp_path):
        hdir = tmp_path / "host-00000"
        hdir.mkdir()
        (hdir / MANIFEST_NAME).write_text(
            json.dumps({"kind": "x"}), encoding="utf-8"
        )
        hp = HostFaultPlan(corrupt_manifest_at=0)
        hp.bind_host(hdir=str(hdir), root=str(tmp_path), epoch=0)
        hp.before_batch(0)
        # flipped bytes are not UTF-8, let alone JSON
        with pytest.raises(ValueError):
            json.loads((hdir / MANIFEST_NAME).read_bytes().decode("utf-8"))

    def test_torn_ledger_tail_keeps_intact_prefix(self, tmp_path):
        path = tmp_path / PROGRESS_NAME
        path.write_text(
            '{"ts": 1.0, "seq": 1, "kind": "elastic",'
            ' "attrs": {"rank": 0, "epoch": 0}}\n',
            encoding="utf-8",
        )
        tear_ledger_tail(path)
        recs = read_progress(path)
        assert [r["seq"] for r in recs] == [1]

    def test_bump_epoch_advances_the_root_marker(self, tmp_path):
        hp = HostFaultPlan(bump_epoch_at=0)
        hp.bind_host(hdir=str(tmp_path / "h"), root=str(tmp_path), epoch=0)
        assert read_epoch(tmp_path) is None
        hp.before_batch(0)
        assert read_epoch(tmp_path)["epoch"] == 1
