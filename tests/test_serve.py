"""Sketch-serving layer (ISSUE PR 10): cross-request coalescing onto
warm compiled plans.

The load-bearing contract: a request's result is BITWISE identical
whether it was served alone (the serial eager path, ``max_coalesce=1``)
or coalesced with strangers into one padded fused dispatch on a
different ladder rung entirely.  The tests below pin that for LS-solve
and KRR-predict (both model kinds), plus the fresh-sketch counter
reservation that makes randomized requests individually reproducible,
the admission/deadline shedding codes (112/113), and the solo-retry
fault ladder (code 108) that keeps one poisoned payload from taking
its batch-mates down.

Every comparison constructs fresh same-seed servers/contexts so
bitwise equality is meaningful (``SketchContext`` is stateful).
"""

import io
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from libskylark_tpu import serve, telemetry
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.ml.kernels import GaussianKernel
from libskylark_tpu.ml.model import FeatureMapModel, KernelModel
from libskylark_tpu.serve import batcher
from libskylark_tpu.sketch.rft import GaussianRFT
from libskylark_tpu.utils import exceptions as ex

pytestmark = pytest.mark.serve

M, N = 64, 5
_rng = np.random.default_rng(1234)
A = _rng.standard_normal((M, N))
RHS = [_rng.standard_normal(M) for _ in range(10)]
XQ = [_rng.standard_normal(12) for _ in range(10)]


def _params(max_coalesce):
    return serve.ServeParams(
        max_coalesce=max_coalesce, warm_start=False, prime=False
    )


def _ls_server(max_coalesce, seed=42):
    srv = serve.Server(_params(max_coalesce), seed=seed)
    srv.registry.register_system("sys", A, context=SketchContext(seed=9))
    return srv


def _feature_map_model():
    ctx = SketchContext(seed=5)
    S = GaussianRFT(12, 32, ctx, sigma=1.2)
    W = np.random.default_rng(7).standard_normal((32, 3))
    return FeatureMapModel([S], W, scale_maps=True)


def _kernel_model():
    rng = np.random.default_rng(8)
    Xt = rng.standard_normal((24, 12))
    Am = rng.standard_normal((24, 3))
    return KernelModel(GaussianKernel(12, sigma=1.1), Xt, Am)


def _run(srv, requests, coalesce):
    """Serial path calls one-at-a-time; coalesced path queues everything
    BEFORE the worker starts, so the whole set arrives as one batch."""
    if coalesce:
        futures = [srv.submit(r) for r in requests]
        srv.start()
        results = [f.result() for f in futures]
    else:
        srv.start()
        results = [srv.call(r) for r in requests]
    srv.stop()
    return results


# ---------------------------------------------------------------------------
# the coalescing bitwise contract


def test_ls_coalesced_bitwise_equals_serial():
    reqs = [serve.make_request("ls_solve", system="sys", b=b) for b in RHS]
    serial = _run(_ls_server(1), reqs, coalesce=False)
    coal = _run(_ls_server(16), [dict(r) for r in reqs], coalesce=True)

    assert all(r["ok"] for r in serial + coal)
    # the batch really coalesced, across a rung boundary: 10 requests
    # ride one 16-wide dispatch while each serial request rode an 8-wide
    assert max(r["trace"]["batch_size"] for r in coal) == len(RHS)
    assert {r["trace"]["bucket"] for r in coal} == {16}
    assert {r["trace"]["bucket"] for r in serial} == {8}
    for s, c in zip(serial, coal):
        assert (np.asarray(s["result"]) == np.asarray(c["result"])).all()


def test_lane_uniform_bucket_skips_remainder_rung():
    # the 12-wide rung is the one ladder rung whose tail columns fall in
    # a remainder vector tile (different gemm micro-kernel, different
    # bits) — coalesced widths skip it
    assert batcher._lane_bucket(1) == 8
    assert batcher._lane_bucket(8) == 8
    assert batcher._lane_bucket(9) == 16
    assert batcher._lane_bucket(12) == 16
    assert batcher._lane_bucket(17) == 24
    assert batcher._lane_bucket(25) == 32
    for k in range(1, 70):
        assert batcher._lane_bucket(k) % 8 == 0


@pytest.mark.parametrize("make_model", [_feature_map_model, _kernel_model],
                         ids=["feature_map", "kernel"])
def test_predict_coalesced_bitwise_equals_serial(make_model):
    def server(max_coalesce):
        srv = serve.Server(_params(max_coalesce), seed=3)
        srv.registry.register_model("mdl", make_model())
        return srv

    reqs = [serve.make_request("predict", model="mdl", x=x) for x in XQ]
    serial = _run(server(1), reqs, coalesce=False)
    coal = _run(server(16), [dict(r) for r in reqs], coalesce=True)

    assert all(r["ok"] for r in serial + coal)
    assert max(r["trace"]["batch_size"] for r in coal) == len(XQ)
    for s, c in zip(serial, coal):
        assert (np.asarray(s["result"]) == np.asarray(c["result"])).all()


def test_fresh_sketch_counter_reservation_isolation():
    """fresh_sketch requests draw counters at ADMISSION (queue order),
    so each request's randomness is pinned regardless of how the batch
    later forms — serial and coalesced servers reserve the same bases
    and produce bitwise-equal per-request results."""
    def run(max_coalesce, coalesce):
        srv = _ls_server(max_coalesce, seed=7)
        reqs = [
            serve.make_request("ls_solve", system="sys", b=b,
                               fresh_sketch=True)
            for b in RHS[:3]
        ]
        return _run(srv, reqs, coalesce)

    serial = run(1, False)
    coal = run(16, True)
    bases_s = [r["trace"]["counter_base"] for r in serial]
    bases_c = [r["trace"]["counter_base"] for r in coal]
    assert bases_s == bases_c
    assert bases_s == sorted(bases_s) and len(set(bases_s)) == 3
    for s, c in zip(serial, coal):
        assert (np.asarray(s["result"]) == np.asarray(c["result"])).all()


# ---------------------------------------------------------------------------
# admission control + shedding


def test_admission_shed_code_112():
    srv = serve.Server(
        serve.ServeParams(max_queue=2, max_coalesce=16,
                          warm_start=False, prime=False),
        seed=1,
    )
    srv.registry.register_system("sys", A, context=SketchContext(seed=9))
    f1 = srv.submit(serve.make_request("ls_solve", system="sys", b=RHS[0]))
    f2 = srv.submit(serve.make_request("ls_solve", system="sys", b=RHS[1]))
    shed = srv.call(op="ls_solve", system="sys", b=RHS[2])
    assert not shed["ok"]
    assert shed["error"]["code"] == 112
    assert shed["error"]["queue_depth"] == 2
    assert shed["error"]["max_depth"] == 2
    with pytest.raises(ex.AdmissionError):
        serve.raise_for_error(shed)
    srv.start()
    assert f1.result()["ok"] and f2.result()["ok"]
    srv.stop()


def test_deadline_shed_code_113():
    srv = _ls_server(16, seed=1)
    fd = srv.submit(
        serve.make_request("ls_solve", system="sys", b=RHS[0], deadline_ms=1)
    )
    time.sleep(0.05)  # let the deadline lapse before the worker drains
    srv.start()
    shed = fd.result()
    srv.stop()
    assert not shed["ok"]
    assert shed["error"]["code"] == 113
    assert shed["error"]["deadline_ms"] == 1
    assert shed["error"]["waited_ms"] > 1
    with pytest.raises(ex.DeadlineExceededError):
        serve.raise_for_error(shed)


# ---------------------------------------------------------------------------
# fault isolation: the serve-side recovery ladder


def test_poisoned_request_isolated_from_batch_mates():
    """Mid-traffic numerical-health fallback: the poisoned request gets a
    structured code-108 verdict with the fallback events in ITS trace;
    its batch-mates complete with bits identical to a clean serial run."""
    reqs = [serve.make_request("ls_solve", system="sys", b=b)
            for b in (RHS[0], RHS[1], RHS[2])]
    serial = _run(_ls_server(1), [dict(r) for r in reqs], coalesce=False)

    bad = RHS[1].copy()
    bad[3] = np.nan
    reqs[1] = serve.make_request("ls_solve", system="sys", b=bad)
    srv = _ls_server(16)
    res = _run(srv, reqs, coalesce=True)

    assert [r["ok"] for r in res] == [True, False, True]
    assert res[1]["error"]["code"] == 108
    kinds = [e["kind"] for e in res[1]["trace"]["events"]]
    assert "fallback" in kinds  # batch-level AND solo-retry visible
    assert (np.asarray(res[0]["result"])
            == np.asarray(serial[0]["result"])).all()
    assert (np.asarray(res[2]["result"])
            == np.asarray(serial[2]["result"])).all()
    # the survivors' traces show they rode the poisoned batch
    assert res[0]["trace"]["coalesced"] and res[2]["trace"]["coalesced"]


# ---------------------------------------------------------------------------
# registry + model loading


def test_registry_unknown_names_are_structured():
    srv = _ls_server(1)
    srv.start()
    r = srv.call(op="ls_solve", system="nope", b=RHS[0])
    assert not r["ok"] and r["error"]["code"] == ex.InvalidParameters("x").code
    assert "sys" in r["error"]["message"]
    r = srv.call(op="predict", model="nope", x=XQ[0])
    assert not r["ok"] and r["error"]["code"] == ex.InvalidParameters("x").code
    srv.stop()


def test_loaded_model_serves_labels(tmp_path):
    model = _feature_map_model()
    model.classes = [10, 20, 30]
    path = str(tmp_path / "clf.json")
    model.save(path)

    srv = serve.Server(_params(16), seed=3)
    srv.registry.load_model("clf", path)
    srv.start()
    client = serve.Client(srv)
    labels = client.predict("clf", XQ[0], labels=True, check=True)
    scores = client.predict("clf", XQ[0], check=True)
    srv.stop()
    assert labels in (10, 20, 30)
    assert np.asarray(labels) == [10, 20, 30][int(np.argmax(scores))]


# ---------------------------------------------------------------------------
# protocol + transports


def test_protocol_error_roundtrip():
    for exc in (
        ex.AdmissionError("full", queue_depth=4, max_depth=4),
        ex.DeadlineExceededError("late", deadline_ms=5, waited_ms=9.5),
        ex.NumericalHealthError("bad", stage="serve_ls_solve"),
    ):
        frame = serve.encode(serve.error_response("r1", exc, {"events": []}))
        back = serve.exception_for(serve.decode(frame)["error"])
        assert type(back) is type(exc)
        assert back.code == exc.code


def test_stdio_transport_round_trip():
    srv = _ls_server(16)
    srv.start()
    lines = [
        serve.encode(serve.make_request("ping")),
        "this is not json",
        serve.encode(serve.make_request("ls_solve", system="sys", b=RHS[0])),
    ]
    out = io.StringIO()
    served = serve.serve_stdio(srv, io.StringIO("\n".join(lines) + "\n"), out)
    srv.stop()
    responses = [json.loads(s) for s in out.getvalue().splitlines()]
    assert served == 2  # the malformed line is answered but not counted
    assert responses[0]["ok"]
    assert not responses[1]["ok"] and responses[1]["error"]["code"] == 100
    assert responses[2]["ok"]
    assert len(responses[2]["result"]) == N


def test_http_loopback_and_batched_post():
    srv = _ls_server(16)
    srv.start()
    httpd = serve.serve_http(srv, port=0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        host, port = httpd.server_address[:2]
        client = serve.Client(url=f"http://{host}:{port}")
        assert client.ping()
        x = client.ls_solve("sys", RHS[0], check=True)
        assert len(x) == N
        # a POSTed list is submitted concurrently -> rides the coalescer
        many = client.call_many([
            serve.make_request("ls_solve", system="sys", b=b.tolist())
            for b in RHS[:4]
        ])
        assert all(r["ok"] for r in many)
        stats = client.stats()
        assert "sys" in stats["registry"]["systems"]
        with urllib.request.urlopen(
            f"http://{host}:{port}/healthz", timeout=10
        ) as r:
            assert json.loads(r.read())["ok"]
    finally:
        httpd.shutdown()
        srv.stop()
    # the remote rows are bit-for-bit the in-process protocol encoding
    serial = _run(_ls_server(1), [
        serve.make_request("ls_solve", system="sys", b=b) for b in RHS[:4]
    ], coalesce=False)
    for remote, local in zip(many, serial):
        assert remote["result"] == np.asarray(local["result"]).tolist()


# ---------------------------------------------------------------------------
# lifecycle + telemetry


def test_prime_compiles_before_traffic_and_stats_report(monkeypatch):
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.REGISTRY.reset()
    srv = serve.Server(
        serve.ServeParams(warm_start=False, prime=True), seed=2
    )
    srv.registry.register_system("sys", A, context=SketchContext(seed=9))
    srv.registry.register_model("mdl", _feature_map_model())
    srv.start()
    assert srv.primed
    r = srv.call(op="ls_solve", system="sys", b=RHS[0])
    assert r["ok"]
    stats = srv.stats()
    srv.stop()
    snap = telemetry.snapshot()
    telemetry.REGISTRY.reset()
    assert stats["params"]["max_coalesce"] == 16
    assert stats["queue_depth"] == 0
    assert stats["counters"].get("requests", 0) >= 1
    # snapshot() folds the serve group with the derived coalesce ratio
    assert snap["serve"]["requests"] >= 1
    assert "coalesce_ratio" in snap["serve"]


def test_stop_resolves_stranded_futures():
    srv = _ls_server(16)
    f = srv.submit(serve.make_request("ls_solve", system="sys", b=RHS[0]))
    # worker never started: stop() must still resolve the future
    srv.stop()
    r = f.result(timeout=5)
    assert not r["ok"] and r["error"]["code"] == 100
