"""Multi-process ``jax.distributed`` execution tests (VERDICT r3 item 4,
widened to world sizes {2, 4, 5} in round 5 per VERDICT r4 item 3).

The reference validates its multi-node paths by running REAL multi-rank
processes on one box (``mpirun -np {1,4,5,7}``, ``tests/unit/
CMakeLists.txt:11-38`` — odd and non-power-of-two counts included, which
is where layout/divisibility bugs live); the analogue here is K OS
processes, each with 2 virtual CPU devices, joined through
``jax.distributed.initialize`` on a localhost coordinator — gloo
collectives actually cross the process boundary.  Covers: world
formation, cross-process psum / psum_scatter / all_to_all, sharded-sketch
parity over the global mesh (P2/P5 — the counter contract makes every
process realize identical operands), the P6 sparse schedule
(``columnwise_sharded_sparse``'s compiled program) with its psum merge
crossing processes, ``timer_report(distributed=True)``, and the
phase-name-mismatch guard.

Skips (not fails) when the runtime cannot form a world in this
environment — distributed CPU support varies across jaxlib builds.
psum_scatter / all_to_all degrade to per-check SKIP lines when gloo
lacks the collective, so one missing primitive cannot mask the rest.
"""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # multi-process worlds: the slow tier

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TIMEOUT_S = 300

_SKIP_MARKERS = (
    "UNIMPLEMENTED",
    "not supported",
    "NotImplementedError",
    "Unable to initialize backend",
    "DEADLINE_EXCEEDED",
    "failed to connect",
    # older jaxlib CPU backends reject any multi-process computation with
    # INVALID_ARGUMENT: "Multiprocess computations aren't implemented"
    "aren't implemented",
)
# The subset that cannot heal between parametrized world sizes (missing
# capability, not a flaky coordinator): only these cache an env skip.
_DETERMINISTIC_MARKERS = (
    "UNIMPLEMENTED",
    "not supported",
    "NotImplementedError",
    "Unable to initialize backend",
    "aren't implemented",
)

# Every rank must print these unconditionally...
_REQUIRED = (
    "world", "psum", "sketch-parity", "sparse-p6", "timer-report",
    "timer-mismatch",
)
# ...and these either pass or print a reasoned per-check SKIP (gloo may
# not implement every collective on CPU; sparse-out rides all_to_all).
_OK_OR_SKIP = ("psum-scatter", "all-to-all", "sparse-out")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# First environment-level skip (world never forms / runtime unsupported)
# is cached so the remaining world sizes skip immediately instead of
# re-waiting out the same formation timeout three times.
_ENV_SKIP: str | None = None


@pytest.mark.parametrize("nprocs", [2, 4, 5])
def test_multi_process_world(nprocs):
    global _ENV_SKIP
    if _ENV_SKIP is not None:
        pytest.skip(_ENV_SKIP)
    port = _free_port()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # child pins cpu itself
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # A fresh XLA_FLAGS: the child appends its own device-count flag and
    # the suite's 8-device flag would skew the expected world size.
    env["XLA_FLAGS"] = ""
    script = os.path.join(_REPO, "tests", "_distributed_child.py")
    procs = [
        subprocess.Popen(
            [sys.executable, script, str(i), str(nprocs), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=_REPO,
        )
        for i in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=_TIMEOUT_S)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        # Timeouts can be transient (loaded box) — skip this size only,
        # don't poison the remaining world sizes.
        pytest.skip(
            f"{nprocs}-process world did not complete within "
            f"{_TIMEOUT_S}s (distributed CPU runtime unavailable here)"
        )

    for rc, out, err in outs:
        if rc != 0 and any(m in err for m in _SKIP_MARKERS):
            reason = (
                "jax.distributed unsupported in this environment: "
                + err.strip().splitlines()[-1][:300]
            )
            # Only deterministic capability markers poison the cache;
            # flaky connect/deadline failures retry at the next size.
            if any(m in err for m in _DETERMINISTIC_MARKERS):
                _ENV_SKIP = reason
            pytest.skip(reason)
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, (
            f"rank {i} failed (rc={rc})\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
        )
        assert "DIST-OK" in out, f"rank {i} incomplete:\n{out}\n{err[-3000:]}"
        for check in _REQUIRED:
            assert f"CHECK {check} OK" in out, (
                f"rank {i} missing {check}:\n{out}"
            )
        for check in _OK_OR_SKIP:
            assert (
                f"CHECK {check} OK" in out or f"CHECK {check} SKIP" in out
            ), f"rank {i} missing {check} (no OK and no SKIP):\n{out}"


# ---------------------------------------------------------------------------
# elastic streaming: kill one rank mid-stream, restart the world, resume
# ---------------------------------------------------------------------------


def _spawn_elastic(nprocs, port, root, out_dir, *, resume, extra_env=None):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # child pins cpu itself
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = ""
    for key in list(env):
        if key.startswith("ELASTIC_"):  # no fault knobs leak across runs
            del env[key]
    if extra_env:
        env.update(extra_env)
    script = os.path.join(_REPO, "tests", "_elastic_child.py")
    return [
        subprocess.Popen(
            [sys.executable, script, str(i), str(nprocs), str(port),
             str(root), str(out_dir), "1" if resume else "0"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=_REPO,
        )
        for i in range(nprocs)
    ]


def _communicate_or_skip(procs, nprocs, what):
    """Reap a full world; env-level failures skip (cached when
    deterministic), real failures assert."""
    global _ENV_SKIP
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=_TIMEOUT_S)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip(
            f"{nprocs}-process {what} run did not complete within "
            f"{_TIMEOUT_S}s (distributed CPU runtime unavailable here)"
        )
    for rc, out, err in outs:
        if rc != 0 and any(m in err for m in _SKIP_MARKERS):
            reason = (
                "jax.distributed unsupported in this environment: "
                + err.strip().splitlines()[-1][:300]
            )
            if any(m in err for m in _DETERMINISTIC_MARKERS):
                _ENV_SKIP = reason
            pytest.skip(reason)
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, (
            f"{what}: rank {i} failed (rc={rc})\nstdout:\n{out}\n"
            f"stderr:\n{err[-3000:]}"
        )
        assert "ELASTIC-OK" in out, (
            f"{what}: rank {i} incomplete:\n{out}\n{err[-3000:]}"
        )
    return outs


@pytest.mark.distributed_streaming
@pytest.mark.parametrize("nprocs", [2, 4])
def test_elastic_kill_one_rank_resume(nprocs, tmp_path):
    """SIGKILL one rank of a distributed streaming pass mid-stream,
    restart the WORLD with ``resume=1``: the merged ``(x, info)`` must
    be bit-identical to an uninterrupted run's, the killed rank must
    replay exactly its uncheckpointed batches, and the survivors must
    replay nothing (verified through the per-host progress ledgers)."""
    import json
    import time

    import numpy as np

    from libskylark_tpu.streaming import RowPartition, host_dir, read_progress
    from libskylark_tpu.streaming.elastic import PROGRESS_NAME

    global _ENV_SKIP
    if _ENV_SKIP is not None:
        pytest.skip(_ENV_SKIP)
    # mirrors _elastic_child.py's problem constants (tests/ is not a
    # package, so the child cannot be imported here)
    nrows, batch_rows = 96, 4
    part = RowPartition(
        nrows=nrows, batch_rows=batch_rows, world_size=nprocs
    )
    kill_rank, kill_after = 1, 1

    # -- run A: uninterrupted reference world -----------------------------
    out_a = tmp_path / "out-a"
    out_a.mkdir()
    procs = _spawn_elastic(
        nprocs, _free_port(), tmp_path / "ck-a", out_a, resume=False
    )
    _communicate_or_skip(procs, nprocs, "reference")

    # -- run B1: same problem, SIGKILL rank 1 after its second commit -----
    root_b = tmp_path / "ck-b"
    out_b1 = tmp_path / "out-b1"
    out_b1.mkdir()
    procs = _spawn_elastic(
        nprocs, _free_port(), root_b, out_b1, resume=False,
        extra_env={
            "ELASTIC_KILL_RANK": str(kill_rank),
            "ELASTIC_KILL_AFTER_CHUNK": str(kill_after),
        },
    )
    try:
        rc = procs[kill_rank].wait(timeout=_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip(
            f"{nprocs}-process kill run did not start within {_TIMEOUT_S}s"
        )
    if rc != -9:  # died before the injected SIGKILL: env problem
        _, err = procs[kill_rank].communicate()
        for p in procs:
            p.kill()
            p.communicate()
        if any(m in err for m in _SKIP_MARKERS):
            pytest.skip(
                "jax.distributed unsupported in this environment: "
                + err.strip().splitlines()[-1][:300]
            )
        raise AssertionError(
            f"killed rank exited rc={rc} before the injected SIGKILL:\n"
            f"{err[-3000:]}"
        )
    # Survivors finish their local folds (the fold is local; only the
    # merge needs the dead rank) — wait for their ledgers' "done", then
    # put them down too: the restart protocol is whole-world.
    survivors = [r for r in range(nprocs) if r != kill_rank]
    deadline = time.monotonic() + _TIMEOUT_S
    pending = set(survivors)
    while pending and time.monotonic() < deadline:
        for r in list(pending):
            recs = read_progress(
                os.path.join(host_dir(root_b, r), PROGRESS_NAME)
            )
            if any(rec["name"] == "done" for rec in recs) \
                    or procs[r].poll() is not None:
                pending.discard(r)
        time.sleep(0.2)
    assert not pending, (
        f"survivor ranks {sorted(pending)} never finished their local "
        "fold after the victim died"
    )
    for r in survivors:
        procs[r].kill()
        procs[r].communicate()

    pre = {
        r: len(read_progress(
            os.path.join(host_dir(root_b, r), PROGRESS_NAME)
        ))
        for r in range(nprocs)
    }

    # -- run B2: restart the whole world with resume ----------------------
    out_b2 = tmp_path / "out-b2"
    out_b2.mkdir()
    procs = _spawn_elastic(
        nprocs, _free_port(), root_b, out_b2, resume=True
    )
    _communicate_or_skip(procs, nprocs, "resume")

    # -- bit-identity: every rank's (x, info) matches the reference -------
    for r in range(nprocs):
        want = np.load(out_a / f"x-{r}.npy")
        got = np.load(out_b2 / f"x-{r}.npy")
        np.testing.assert_array_equal(got, want)
        with open(out_a / f"info-{r}.json") as fh:
            winfo = json.load(fh)
        with open(out_b2 / f"info-{r}.json") as fh:
            ginfo = json.load(fh)
        assert ginfo == winfo
    # ...and x is identical ACROSS ranks (psum merge, no broadcast)
    x0 = np.load(out_b2 / "x-0.npy")
    for r in range(1, nprocs):
        np.testing.assert_array_equal(np.load(out_b2 / f"x-{r}.npy"), x0)

    # -- replay accounting via the per-host ledgers -----------------------
    # checkpoint_every=1 and the SIGKILL lands after commit `kill_after`,
    # so the victim has kill_after+1 batches on disk and must replay
    # exactly nlocal - (kill_after+1); survivors checkpointed everything
    # and replay nothing.
    for r in range(nprocs):
        recs = read_progress(
            os.path.join(host_dir(root_b, r), PROGRESS_NAME)
        )
        new = recs[pre[r]:]
        folded = [rec["attrs"]["batch"] for rec in new
                  if rec["name"] == "batch"]
        b0, b1 = part.batch_range(r)
        nlocal = b1 - b0
        if r == kill_rank:
            assert folded == list(range(b0 + kill_after + 1, b1))
        else:
            assert folded == []
        done = [rec for rec in new if rec["name"] == "done"]
        assert len(done) == 1 and done[0]["attrs"]["batches"] == nlocal


# ---------------------------------------------------------------------------
# repartition-on-resume: kill a rank, resume at a DIFFERENT world size
# ---------------------------------------------------------------------------


def _run_world_with_casualty(nprocs, root, out_dir, *, kill_rank,
                             kill_after, extra_env=None):
    """Run a world with one rank SIGKILLed mid-stream; wait for the
    survivors to finish their LOCAL folds (ledger ``done``), then put
    them down too.  Leaves the shared root exactly as a real preemption
    would: survivors fully checkpointed, the victim partially."""
    import time

    from libskylark_tpu.streaming import host_dir, read_progress
    from libskylark_tpu.streaming.elastic import PROGRESS_NAME

    env = {
        "ELASTIC_KILL_RANK": str(kill_rank),
        "ELASTIC_KILL_AFTER_CHUNK": str(kill_after),
    }
    env.update(extra_env or {})
    procs = _spawn_elastic(
        nprocs, _free_port(), root, out_dir, resume=False, extra_env=env
    )
    try:
        rc = procs[kill_rank].wait(timeout=_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip(
            f"{nprocs}-process kill run did not start within {_TIMEOUT_S}s"
        )
    if rc != -9:  # died before the injected SIGKILL: env problem
        _, err = procs[kill_rank].communicate()
        for p in procs:
            p.kill()
            p.communicate()
        if any(m in err for m in _SKIP_MARKERS):
            pytest.skip(
                "jax.distributed unsupported in this environment: "
                + err.strip().splitlines()[-1][:300]
            )
        raise AssertionError(
            f"killed rank exited rc={rc} before the injected SIGKILL:\n"
            f"{err[-3000:]}"
        )
    survivors = [r for r in range(nprocs) if r != kill_rank]
    deadline = time.monotonic() + _TIMEOUT_S
    pending = set(survivors)
    while pending and time.monotonic() < deadline:
        for r in list(pending):
            recs = read_progress(
                os.path.join(host_dir(root, r), PROGRESS_NAME)
            )
            if any(rec["name"] == "done" for rec in recs) \
                    or procs[r].poll() is not None:
                pending.discard(r)
        time.sleep(0.2)
    assert not pending, (
        f"survivor ranks {sorted(pending)} never finished their local "
        "fold after the victim died"
    )
    for r in survivors:
        procs[r].kill()
        procs[r].communicate()


def _resize_resume_scenario(tmp_path, *, old_world, new_world, kill_rank,
                            kill_after):
    """Kill one rank of an ``old_world`` run, resume on ``new_world``
    ranks with ``resume_policy=repartition``: the merged ``x`` must be
    bit-identical to an UNINTERRUPTED run at the new world size (exact
    integer + CWT arithmetic makes that a hard equality), and
    ``info["replay"]`` must show only the dead rank's unledgered batch
    range re-folded."""
    import json

    import numpy as np

    from libskylark_tpu.streaming import RowPartition

    global _ENV_SKIP
    if _ENV_SKIP is not None:
        pytest.skip(_ENV_SKIP)
    exact = {"ELASTIC_EXACT": "1"}

    # -- reference: uninterrupted run at the NEW world size ---------------
    out_ref = tmp_path / "out-ref"
    out_ref.mkdir()
    procs = _spawn_elastic(
        new_world, _free_port(), tmp_path / "ck-ref", out_ref,
        resume=False, extra_env=exact,
    )
    _communicate_or_skip(procs, new_world, "reference")

    # -- casualty run at the OLD world size -------------------------------
    root = tmp_path / "ck"
    _run_world_with_casualty(
        old_world, root, tmp_path, kill_rank=kill_rank,
        kill_after=kill_after, extra_env=exact,
    )

    # -- resume at the NEW world size with repartition ---------------------
    out_res = tmp_path / "out-res"
    out_res.mkdir()
    procs = _spawn_elastic(
        new_world, _free_port(), root, out_res, resume=True,
        extra_env={**exact, "ELASTIC_RESUME_POLICY": "repartition"},
    )
    _communicate_or_skip(procs, new_world, "repartition-resume")

    # bit-identity at the new world size, on every rank
    for r in range(new_world):
        want = np.load(out_ref / f"x-{r}.npy")
        got = np.load(out_res / f"x-{r}.npy")
        np.testing.assert_array_equal(got, want)

    # replay accounting: only the victim's unledgered range re-folds.
    # mirrors _elastic_child.py's constants (tests/ is not a package)
    old_part = RowPartition(nrows=96, batch_rows=4, world_size=old_world)
    b0, b1 = old_part.batch_range(kill_rank)
    want_replayed = [[b0 + kill_after + 1, b1]]
    for r in range(new_world):
        with open(out_res / f"info-{r}.json") as fh:
            info = json.load(fh)
        replay = info["replay"]
        assert replay["replayed"] == want_replayed
        assert replay["from_world"] == old_world
        assert replay["to_world"] == new_world
        assert replay["lost_hosts"] == []


@pytest.mark.distributed_streaming
def test_elastic_shrink_world_resume(tmp_path):
    """4-host run loses a rank; the job comes back on 2 hosts."""
    _resize_resume_scenario(
        tmp_path, old_world=4, new_world=2, kill_rank=1, kill_after=1
    )


@pytest.mark.distributed_streaming
def test_elastic_grow_world_resume(tmp_path):
    """2-host run loses a rank; the job comes back on 4 hosts."""
    _resize_resume_scenario(
        tmp_path, old_world=2, new_world=4, kill_rank=1, kill_after=1
    )


@pytest.mark.distributed_streaming
def test_elastic_hung_rank_raises_timeout(tmp_path):
    """A straggler sleeping through its fold must NOT hang the world:
    the healthy rank's deadline-bounded merge raises
    ``CollectiveTimeoutError`` (code 110) naming the straggler."""
    global _ENV_SKIP
    if _ENV_SKIP is not None:
        pytest.skip(_ENV_SKIP)
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    procs = _spawn_elastic(
        2, _free_port(), tmp_path / "ck", out_dir, resume=False,
        extra_env={
            "ELASTIC_FAULT_RANK": "1",
            "ELASTIC_SLOW_AT_BATCH": "0",
            "ELASTIC_SLOW_SECONDS": "600",
            "ELASTIC_COLLECTIVE_TIMEOUT_S": "15",
        },
    )
    try:
        rc0 = procs[0].wait(timeout=_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
            p.communicate()
        pytest.skip(
            f"timeout scenario did not complete within {_TIMEOUT_S}s "
            "(distributed CPU runtime unavailable here)"
        )
    out0, err0 = procs[0].communicate()
    procs[1].kill()  # still asleep in its injected stall
    procs[1].communicate()
    if rc0 != 110 and any(m in err0 for m in _SKIP_MARKERS):
        reason = (
            "jax.distributed unsupported in this environment: "
            + err0.strip().splitlines()[-1][:300]
        )
        if any(m in err0 for m in _DETERMINISTIC_MARKERS):
            _ENV_SKIP = reason
        pytest.skip(reason)
    assert rc0 == 110, (
        f"healthy rank should exit 110 (CollectiveTimeoutError), got "
        f"rc={rc0}\nstdout:\n{out0}\nstderr:\n{err0[-3000:]}"
    )
    assert "ELASTIC-TIMEOUT" in out0
    assert "stragglers=[1]" in out0


# ---------------------------------------------------------------------------
# distributed TRAINING: SIGKILL one rank mid-stream, resume the world
# ---------------------------------------------------------------------------


@pytest.mark.distributed_streaming
def test_train_kill_one_rank_resume_bitwise(tmp_path):
    """SIGKILL one rank of a distributed BlockADMM TRAINING run during
    its feature-streaming pass, restart the world with ``resume=1``: the
    trained model ``W`` must be bit-identical to an uninterrupted run's
    on every rank (``ELASTIC_TRAIN=1`` drives ``_elastic_child.py``'s
    train scenario; same ``x-<rank>.npy`` artifact contract as the
    streaming kill test)."""
    import json
    import time

    import numpy as np

    from libskylark_tpu.streaming import host_dir, read_progress
    from libskylark_tpu.streaming.elastic import PROGRESS_NAME

    global _ENV_SKIP
    if _ENV_SKIP is not None:
        pytest.skip(_ENV_SKIP)
    nprocs, kill_rank, kill_after = 2, 1, 1
    train_env = {"ELASTIC_TRAIN": "1"}

    # -- run A: uninterrupted reference world -----------------------------
    out_a = tmp_path / "out-a"
    out_a.mkdir()
    procs = _spawn_elastic(
        nprocs, _free_port(), tmp_path / "ck-a", out_a, resume=False,
        extra_env=train_env,
    )
    _communicate_or_skip(procs, nprocs, "train reference")

    # -- run B1: SIGKILL rank 1 mid-stream ---------------------------------
    root_b = tmp_path / "ck-b"
    out_b1 = tmp_path / "out-b1"
    out_b1.mkdir()
    procs = _spawn_elastic(
        nprocs, _free_port(), root_b, out_b1, resume=False,
        extra_env={
            **train_env,
            "ELASTIC_KILL_RANK": str(kill_rank),
            "ELASTIC_KILL_AFTER_CHUNK": str(kill_after),
        },
    )
    try:
        rc = procs[kill_rank].wait(timeout=_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
            p.communicate()
        pytest.skip(
            f"train kill run did not start within {_TIMEOUT_S}s"
        )
    if rc != -9:
        _, err = procs[kill_rank].communicate()
        for p in procs:
            p.kill()
            p.communicate()
        if any(m in err for m in _SKIP_MARKERS):
            pytest.skip(
                "jax.distributed unsupported in this environment: "
                + err.strip().splitlines()[-1][:300]
            )
        raise AssertionError(
            f"killed rank exited rc={rc} before the injected SIGKILL:\n"
            f"{err[-3000:]}"
        )
    # The survivor finishes its local STREAM fold, then blocks in the
    # first consensus psum waiting on the dead rank — wait for its
    # ledger's "done", then put it down (whole-world restart protocol).
    survivor = 1 - kill_rank
    deadline = time.monotonic() + _TIMEOUT_S
    while time.monotonic() < deadline:
        recs = read_progress(
            os.path.join(host_dir(root_b, survivor), PROGRESS_NAME)
        )
        if any(rec["name"] == "done" for rec in recs) \
                or procs[survivor].poll() is not None:
            break
        time.sleep(0.2)
    procs[survivor].kill()
    procs[survivor].communicate()

    # -- run B2: restart the whole world with resume ----------------------
    out_b2 = tmp_path / "out-b2"
    out_b2.mkdir()
    procs = _spawn_elastic(
        nprocs, _free_port(), root_b, out_b2, resume=True,
        extra_env=train_env,
    )
    _communicate_or_skip(procs, nprocs, "train resume")

    # -- bit-identity: every rank's model matches the reference -----------
    for r in range(nprocs):
        want = np.load(out_a / f"x-{r}.npy")
        got = np.load(out_b2 / f"x-{r}.npy")
        np.testing.assert_array_equal(got, want)
        with open(out_a / f"info-{r}.json") as fh:
            winfo = json.load(fh)
        with open(out_b2 / f"info-{r}.json") as fh:
            ginfo = json.load(fh)
        assert ginfo == winfo
    # ...and W is identical ACROSS ranks (consensus psum, no broadcast)
    w0 = np.load(out_b2 / "x-0.npy")
    for r in range(1, nprocs):
        np.testing.assert_array_equal(np.load(out_b2 / f"x-{r}.npy"), w0)
