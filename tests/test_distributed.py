"""Multi-process ``jax.distributed`` execution tests (VERDICT r3 item 4,
widened to world sizes {2, 4, 5} in round 5 per VERDICT r4 item 3).

The reference validates its multi-node paths by running REAL multi-rank
processes on one box (``mpirun -np {1,4,5,7}``, ``tests/unit/
CMakeLists.txt:11-38`` — odd and non-power-of-two counts included, which
is where layout/divisibility bugs live); the analogue here is K OS
processes, each with 2 virtual CPU devices, joined through
``jax.distributed.initialize`` on a localhost coordinator — gloo
collectives actually cross the process boundary.  Covers: world
formation, cross-process psum / psum_scatter / all_to_all, sharded-sketch
parity over the global mesh (P2/P5 — the counter contract makes every
process realize identical operands), the P6 sparse schedule
(``columnwise_sharded_sparse``'s compiled program) with its psum merge
crossing processes, ``timer_report(distributed=True)``, and the
phase-name-mismatch guard.

Skips (not fails) when the runtime cannot form a world in this
environment — distributed CPU support varies across jaxlib builds.
psum_scatter / all_to_all degrade to per-check SKIP lines when gloo
lacks the collective, so one missing primitive cannot mask the rest.
"""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # multi-process worlds: the slow tier

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TIMEOUT_S = 300

_SKIP_MARKERS = (
    "UNIMPLEMENTED",
    "not supported",
    "NotImplementedError",
    "Unable to initialize backend",
    "DEADLINE_EXCEEDED",
    "failed to connect",
)
# The subset that cannot heal between parametrized world sizes (missing
# capability, not a flaky coordinator): only these cache an env skip.
_DETERMINISTIC_MARKERS = (
    "UNIMPLEMENTED",
    "not supported",
    "NotImplementedError",
    "Unable to initialize backend",
)

# Every rank must print these unconditionally...
_REQUIRED = (
    "world", "psum", "sketch-parity", "sparse-p6", "timer-report",
    "timer-mismatch",
)
# ...and these either pass or print a reasoned per-check SKIP (gloo may
# not implement every collective on CPU; sparse-out rides all_to_all).
_OK_OR_SKIP = ("psum-scatter", "all-to-all", "sparse-out")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# First environment-level skip (world never forms / runtime unsupported)
# is cached so the remaining world sizes skip immediately instead of
# re-waiting out the same formation timeout three times.
_ENV_SKIP: str | None = None


@pytest.mark.parametrize("nprocs", [2, 4, 5])
def test_multi_process_world(nprocs):
    global _ENV_SKIP
    if _ENV_SKIP is not None:
        pytest.skip(_ENV_SKIP)
    port = _free_port()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # child pins cpu itself
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # A fresh XLA_FLAGS: the child appends its own device-count flag and
    # the suite's 8-device flag would skew the expected world size.
    env["XLA_FLAGS"] = ""
    script = os.path.join(_REPO, "tests", "_distributed_child.py")
    procs = [
        subprocess.Popen(
            [sys.executable, script, str(i), str(nprocs), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=_REPO,
        )
        for i in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=_TIMEOUT_S)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        # Timeouts can be transient (loaded box) — skip this size only,
        # don't poison the remaining world sizes.
        pytest.skip(
            f"{nprocs}-process world did not complete within "
            f"{_TIMEOUT_S}s (distributed CPU runtime unavailable here)"
        )

    for rc, out, err in outs:
        if rc != 0 and any(m in err for m in _SKIP_MARKERS):
            reason = (
                "jax.distributed unsupported in this environment: "
                + err.strip().splitlines()[-1][:300]
            )
            # Only deterministic capability markers poison the cache;
            # flaky connect/deadline failures retry at the next size.
            if any(m in err for m in _DETERMINISTIC_MARKERS):
                _ENV_SKIP = reason
            pytest.skip(reason)
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, (
            f"rank {i} failed (rc={rc})\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
        )
        assert "DIST-OK" in out, f"rank {i} incomplete:\n{out}\n{err[-3000:]}"
        for check in _REQUIRED:
            assert f"CHECK {check} OK" in out, (
                f"rank {i} missing {check}:\n{out}"
            )
        for check in _OK_OR_SKIP:
            assert (
                f"CHECK {check} OK" in out or f"CHECK {check} SKIP" in out
            ), f"rank {i} missing {check} (no OK and no SKIP):\n{out}"
