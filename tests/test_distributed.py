"""Two-process ``jax.distributed`` execution test (VERDICT r3 item 4).

The reference validates its multi-node paths by running REAL multi-rank
processes on one box (``mpirun -np K``, ``tests/unit/CMakeLists.txt:
11-38``); the analogue here is two OS processes, each with 2 virtual CPU
devices, joined through ``jax.distributed.initialize`` on a localhost
coordinator — gloo collectives actually cross the process boundary.
Covers: world formation, cross-process psum, sharded-sketch parity over
the global mesh (P2/P5 — the counter contract makes both processes
realize identical operands), ``timer_report(distributed=True)`` at world
size 2, and the phase-name-mismatch guard.

Skips (not fails) when the runtime cannot form a world in this
environment — distributed CPU support varies across jaxlib builds.
"""

import os
import socket
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TIMEOUT_S = 240

_SKIP_MARKERS = (
    "UNIMPLEMENTED",
    "not supported",
    "NotImplementedError",
    "Unable to initialize backend",
    "DEADLINE_EXCEEDED",
    "failed to connect",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_world():
    port = _free_port()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # child pins cpu itself
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # A fresh XLA_FLAGS: the child appends its own device-count flag and
    # the suite's 8-device flag would skew the expected world size.
    env["XLA_FLAGS"] = ""
    script = os.path.join(_REPO, "tests", "_distributed_child.py")
    procs = [
        subprocess.Popen(
            [sys.executable, script, str(i), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=_REPO,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=_TIMEOUT_S)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip(
            "two-process world did not complete within "
            f"{_TIMEOUT_S}s (distributed CPU runtime unavailable here)"
        )

    for rc, out, err in outs:
        if rc != 0 and any(m in err for m in _SKIP_MARKERS):
            pytest.skip(
                "jax.distributed unsupported in this environment: "
                + err.strip().splitlines()[-1][:300]
            )
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, (
            f"rank {i} failed (rc={rc})\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
        )
        assert "DIST-OK" in out, f"rank {i} incomplete:\n{out}\n{err[-3000:]}"
        for check in (
            "world", "psum", "sketch-parity", "timer-report", "timer-mismatch"
        ):
            assert f"CHECK {check} OK" in out, f"rank {i} missing {check}:\n{out}"
