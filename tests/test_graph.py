"""Graph analytics tests: ASE recovers block structure; community
detection finds planted communities; spectral utilities match reference
formulas; HDF5/arc-list IO round-trips."""

import numpy as np
import pytest

from libskylark_tpu import SketchContext
from libskylark_tpu.graph import (
    ASEParams,
    SimpleGraph,
    approximate_ase,
    find_local_cluster,
    read_arc_list,
    time_dependent_ppr,
)
from libskylark_tpu.io import read_hdf5, write_hdf5
from libskylark_tpu.linalg.spectral import chebyshev_diff_matrix, chebyshev_points

pytestmark = pytest.mark.graph


def two_community_graph(rng, n_per=30, p_in=0.5, p_out=0.02):
    n = 2 * n_per
    edges = []
    for i in range(n):
        for j in range(i + 1, n):
            same = (i < n_per) == (j < n_per)
            if rng.random() < (p_in if same else p_out):
                edges.append((i, j))
    return SimpleGraph(edges), n_per


class TestSpectralUtils:
    def test_chebyshev_points_range(self):
        x = chebyshev_points(9, 0.0, 5.0)
        assert x[0] == 5.0 and x[-1] == 0.0
        assert x[4] == 2.5  # midpoint exact
        assert np.all(np.diff(x) < 0)

    def test_diff_matrix_differentiates_polynomials(self):
        N = 12
        D, x = chebyshev_diff_matrix(N, 0.0, 2.0)
        p = x**3 - 2 * x
        dp = 3 * x**2 - 2
        np.testing.assert_allclose(D @ p, dp, rtol=1e-8, atol=1e-8)

    def test_diff_matrix_standard_interval(self):
        D, x = chebyshev_diff_matrix(8)
        p = np.exp(x)
        np.testing.assert_allclose(D @ p, p, rtol=1e-3)


class TestSimpleGraph:
    def test_build_and_accessors(self):
        G = SimpleGraph([("a", "b"), ("b", "c"), ("a", "b"), ("c", "c")])
        assert G.n == 3
        assert G.volume == 4  # 2 edges * 2
        b = G.index["b"]
        assert G.degree(b) == 2

    def test_arc_list_io(self, tmp_path):
        (tmp_path / "g").write_text("# comment\n1 2\n2 3\n3 1\n")
        G = read_arc_list(tmp_path / "g")
        assert G.n == 3 and G.volume == 6

    def test_adjacency_forms_match(self, rng):
        G, _ = two_community_graph(rng, 10)
        Ad = G.adjacency()
        Ab = np.asarray(G.adjacency_bcoo().todense())
        np.testing.assert_array_equal(Ad, Ab)
        np.testing.assert_array_equal(Ad, Ad.T)


class TestASE:
    def test_recovers_two_blocks(self, rng):
        G, n_per = two_community_graph(rng, 30, p_in=0.7, p_out=0.02)
        X, lam = approximate_ase(
            G, 2, SketchContext(seed=1), ASEParams(num_iterations=3)
        )
        X = np.asarray(X)
        # 2-means on the embedding should separate the blocks: use the
        # sign of the dim best correlated with membership.
        labels = np.array([0] * n_per + [1] * n_per)
        # vertices are insertion-ordered ints 0..n-1
        order = np.argsort([G.index[i] for i in sorted(G.index)])
        sep = 0
        for dim in range(2):
            pred = (X[:, dim] > np.median(X[:, dim])).astype(int)
            acc = max((pred == labels).mean(), (pred != labels).mean())
            sep = max(sep, acc)
        assert sep > 0.9

    def test_sparse_adjacency_path(self, rng):
        G, _ = two_community_graph(rng, 15)
        Xd, _ = approximate_ase(G, 2, SketchContext(seed=2))
        Xs, _ = approximate_ase(
            G, 2, SketchContext(seed=2), ASEParams(sparse=True)
        )
        np.testing.assert_allclose(
            np.abs(np.asarray(Xd)), np.abs(np.asarray(Xs)), rtol=1e-4, atol=1e-6
        )


class TestCommunity:
    def test_ppr_mass_concentrates_near_seed(self, rng):
        G, n_per = two_community_graph(rng, 25)
        times, Y = time_dependent_ppr(G, {0: 1.0})
        assert Y.shape[0] == 4
        in_mass = Y[:, :n_per].sum(axis=1)
        out_mass = Y[:, n_per:].sum(axis=1)
        assert np.all(in_mass > out_mass)

    def test_finds_planted_community(self, rng):
        G, n_per = two_community_graph(rng, 25)
        cluster, cond = find_local_cluster(G, [0, 1])
        inside = sum(1 for v in cluster if v < n_per)
        assert inside / max(len(cluster), 1) > 0.8
        assert cond < 0.5

    def test_recursive_no_worse(self, rng):
        G, n_per = two_community_graph(rng, 20)
        _, c1 = find_local_cluster(G, [0])
        _, c2 = find_local_cluster(G, [0], recursive=True)
        assert c2 <= c1 + 1e-12

    def test_locality_large_graph(self, rng):
        """Work scales with the cluster, not the graph (≙ the push-queue
        locality of local_computations.hpp:140-250): a planted 60-vertex
        cluster in a ~200k-edge background is recovered in well under a
        second of diffusion+sweep time."""
        import time

        from libskylark_tpu.graph.graph import SimpleGraph

        n_bg, m_bg, nc = 40_000, 200_000, 60
        e_bg = rng.integers(0, n_bg, (m_bg, 2))
        e_in = np.argwhere(rng.random((nc, nc)) < 0.5)
        e_out = np.stack(
            [rng.integers(0, nc, 150), rng.integers(nc, n_bg, 150)], 1
        )
        edges = np.vstack([e_bg, e_in, e_out])
        G = SimpleGraph(map(tuple, edges.tolist()))
        seeds = [G.index[i] for i in range(3) if i in G.index]
        t0 = time.perf_counter()
        times, Y = __import__(
            "libskylark_tpu.graph.community", fromlist=["time_dependent_ppr"]
        ).time_dependent_ppr(
            G, {v: 1.0 / len(seeds) for v in seeds}, epsilon=1e-4
        )
        dt = time.perf_counter() - t0
        # Locality, asserted structurally: the diffusion's support stays a
        # tiny fraction of the graph (push-bound truncation), so work
        # scaled with the cluster, not with n.
        support = np.flatnonzero(np.abs(Y).max(axis=0) > 0)
        assert support.size < G.n // 20
        cluster, cond = find_local_cluster(G, seeds, epsilon=1e-4)
        names = {G.vertices[v] for v in cluster}
        inside = sum(1 for v in names if isinstance(v, int) and v < nc)
        assert inside / max(len(cluster), 1) > 0.9
        assert cond < 0.4
        assert dt < 30.0  # generous wall bound; locality is the real check


class TestHDF5:
    def test_dense_roundtrip(self, tmp_path, rng):
        X = rng.standard_normal((20, 6))
        y = rng.standard_normal(20)
        write_hdf5(tmp_path / "d.h5", X, y)
        X2, y2 = read_hdf5(tmp_path / "d.h5")
        np.testing.assert_allclose(X2, X)
        np.testing.assert_allclose(y2, y)

    def test_sparse_roundtrip(self, tmp_path, rng):
        X = rng.standard_normal((15, 8))
        X[rng.random((15, 8)) < 0.6] = 0
        y = rng.integers(0, 2, 15).astype(float)
        write_hdf5(tmp_path / "s.h5", X, y, sparse=True)
        Xs, y2 = read_hdf5(tmp_path / "s.h5")
        np.testing.assert_allclose(np.asarray(Xs.todense()), X)
        Xd, _ = read_hdf5(tmp_path / "s.h5", sparse=False)
        np.testing.assert_allclose(Xd, X)


class TestGraphCLIs:
    def test_graph_se_cli(self, tmp_path, rng, monkeypatch, capsys):
        from libskylark_tpu.cli.graph_se import main

        G, _ = two_community_graph(rng, 15)
        lines = []
        for i in range(G.n):
            for j in G.neighbors(i):
                if i < j:
                    lines.append(f"{i} {j}")
        (tmp_path / "g").write_text("\n".join(lines) + "\n")
        monkeypatch.chdir(tmp_path)
        rc = main([str(tmp_path / "g"), "-k", "2", "--prefix", "emb"])
        assert rc == 0
        X = np.load(tmp_path / "emb.X.npy")
        assert X.shape[1] == 2

    def test_community_cli(self, tmp_path, rng, capsys):
        from libskylark_tpu.cli.community import main

        G, _ = two_community_graph(rng, 15)
        lines = []
        for i in range(G.n):
            for j in G.neighbors(i):
                if i < j:
                    lines.append(f"{i} {j}")
        (tmp_path / "g").write_text("\n".join(lines) + "\n")
        rc = main([str(tmp_path / "g"), "--seed", "0"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "Conductance:" in out and "Cluster:" in out

    def test_convert2hdf5_cli(self, tmp_path, rng):
        from libskylark_tpu.cli.convert2hdf5 import main
        from libskylark_tpu.io import write_libsvm

        X = rng.standard_normal((10, 4))
        write_libsvm(tmp_path / "f", X, np.ones(10))
        rc = main([str(tmp_path / "f"), str(tmp_path / "f.h5")])
        assert rc == 0
        X2, y2 = read_hdf5(tmp_path / "f.h5")
        np.testing.assert_allclose(X2, X, rtol=1e-15)
