"""Certified mixed-precision refinement (ISSUE PR 14): the refine
route, its guard/ladder integration, the policy earning/retirement
contract, the served cond-est endpoint, and the quasirandom sketch's
interchange.

Load-bearing pins:

- route-OFF bitwise parity — exercising the refine machinery must not
  perturb the default sketch route by a single bit;
- certified convergence — the gate only passes on a freshly recomputed
  residual and the answer matches the exact solve;
- stagnation falls down the EXISTING ladder (resketch → grow → exact
  dense) under guarding, raises code 115 without it;
- the policy earns the refine route only from recorded certified refine
  history and a single stagnation retires it;
- served cond-est results are identical solo vs coalesced;
- the QJLT sketch round-trips through the JSON interchange bitwise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import enable_x64

from libskylark_tpu import plans, policy, serve
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.linalg.least_squares import approximate_least_squares
from libskylark_tpu.policy.decide import (
    LS_ROUTES,
    ProblemSignature,
    choose_route,
)
from libskylark_tpu.policy.profile import load_entries
from libskylark_tpu.resilient import FaultPlan
from libskylark_tpu.solvers.refine import RefineParams, refine_least_squares
from libskylark_tpu.utils import exceptions as ex

pytestmark = pytest.mark.refine


def _ls_problem(seed=5, m=400, n=16, dtype=np.float64, noise=1e-3):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((m, n)).astype(dtype)
    x_true = rng.standard_normal(n).astype(dtype)
    b = (A @ x_true + noise * rng.standard_normal(m)).astype(dtype)
    return jnp.asarray(A), jnp.asarray(b)


# ---------------------------------------------------------------------------
# route-OFF bitwise parity


def test_route_off_bitwise_parity():
    """The sketch route must be bit-identical before and after the
    refine machinery runs: refine draws from its own context, so the
    default route's sketch stream (and the plan cache it warms) is
    untouched."""
    A, b = _ls_problem(dtype=np.float32)
    x_before = np.asarray(
        approximate_least_squares(A, b, SketchContext(seed=7))
    )
    X, info = refine_least_squares(A, b, SketchContext(seed=31))
    assert info["refine"]["converged"]
    x_after = np.asarray(
        approximate_least_squares(A, b, SketchContext(seed=7))
    )
    assert np.array_equal(x_before, x_after)


def test_refine_is_an_explicit_route():
    assert "refine" in LS_ROUTES
    A, b = _ls_problem(dtype=np.float32)
    x, info = approximate_least_squares(
        A, b, SketchContext(seed=7), route="refine", return_info=True
    )
    assert info["policy"]["route"] == "refine"
    assert info["refine"]["converged"]
    assert np.all(np.isfinite(np.asarray(x)))


# ---------------------------------------------------------------------------
# certified convergence


def test_certified_convergence_f64():
    """f64 inputs refine to the exact solve's accuracy through an f32
    factorization: the gate only passes on a freshly recomputed
    residual, so convergence is certified, not assumed."""
    with enable_x64():
        A, b = _ls_problem()
        X, info = refine_least_squares(A, b, SketchContext(seed=11))
        rf = info["refine"]
        assert rf["converged"] and rf["halt"] == "converged"
        assert rf["rung"] == "f32"  # f64 never silently demotes to bf16
        assert rf["iters"] >= 1
        assert rf["gradient_norm"] <= rf["gate"]
        xs = np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]
        r_exact = np.linalg.norm(np.asarray(A) @ xs - np.asarray(b))
        r_ref = float(jnp.linalg.norm(A @ X - b))
        assert r_ref <= r_exact * (1 + 1e-9)


def test_f32_inputs_ride_bf16_rung():
    A, b = _ls_problem(dtype=np.float32)
    X, info = refine_least_squares(A, b, SketchContext(seed=11))
    assert info["refine"]["rung"] == "bf16+f32"
    assert info["refine"]["converged"]
    xs = np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]
    r_exact = np.linalg.norm(np.asarray(A) @ xs - np.asarray(b))
    r_ref = float(jnp.linalg.norm(A @ X - b))
    assert r_ref <= r_exact * (1 + 1e-4)


def test_sketch_cannot_shrink_reports_exact():
    """s >= m: the honest answer is the exact solve, reported as such."""
    A, b = _ls_problem(m=24, n=16)
    X, info = refine_least_squares(A, b, SketchContext(seed=3))
    assert info["refine"]["rung"] == "exact-f64"
    assert info["refine"]["iters"] == 0


# ---------------------------------------------------------------------------
# stagnation: ladder under guarding, code 115 without


def test_stagnation_falls_down_ladder_to_exact():
    """A refinement that cannot meet its gate (one sweep, impossible
    rtol) demotes every attempt to RESKETCH; the EXISTING ladder walks
    fresh-seed → grow → exact dense, and the caller still gets the
    right answer with the fallback on the record."""
    A, b = _ls_problem(dtype=np.float32)
    X, info = refine_least_squares(
        A, b, SketchContext(seed=7),
        RefineParams(max_iters=1, rtol=1e-300),
    )
    rf = info["refine"]
    assert rf["halt"] == "fallback" and rf["rung"] == "exact-f64"
    rec = info["recovery"]
    assert rec["guarded"]
    assert any(a["verdict"] == "RESKETCH" for a in rec["attempts"])
    xs = np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]
    np.testing.assert_allclose(np.asarray(X), xs, rtol=1e-3, atol=1e-4)


def test_transient_corruption_recovers_via_resketch():
    """A one-shot corrupted sketch (FaultPlan attempt-0 NaN) certifies
    RESKETCH and attempt 1 converges on a fresh seed."""
    A, b = _ls_problem(dtype=np.float32)
    X, info = refine_least_squares(
        A, b, SketchContext(seed=7), fault_plan=FaultPlan(nan_at=0)
    )
    rec = info["recovery"]
    assert rec["attempts"][0]["verdict"] == "RESKETCH"
    assert info["refine"]["converged"]
    assert np.all(np.isfinite(np.asarray(X)))


def test_guard_off_stagnation_raises_115(monkeypatch):
    monkeypatch.setenv("SKYLARK_GUARD", "0")
    A, b = _ls_problem(dtype=np.float32)
    with pytest.raises(ex.RefinementError) as e:
        refine_least_squares(
            A, b, SketchContext(seed=7),
            RefineParams(max_iters=1, rtol=1e-300),
        )
    assert e.value.code == 115
    assert ex.RefinementError.code == 115


# ---------------------------------------------------------------------------
# policy: earned from history, retired on stagnation


@pytest.fixture
def policy_env(tmp_path, monkeypatch):
    monkeypatch.setenv("SKYLARK_POLICY", "1")
    monkeypatch.setenv("SKYLARK_GUARD", "1")
    monkeypatch.setenv("SKYLARK_POLICY_MIN_SAMPLES", "3")
    monkeypatch.delenv("SKYLARK_POLICY_DIR", raising=False)
    store = str(tmp_path / "policy-store")
    policy.configure(store)
    policy.reset()
    policy.invalidate_cache()
    plans.clear()
    yield store
    policy.configure(None)
    policy.reset()
    policy.invalidate_cache()


def test_refine_route_earned_from_certified_history(policy_env):
    A, b = _ls_problem(dtype=np.float32, m=240, n=8)
    for _ in range(4):
        _, info = approximate_least_squares(
            A, b, SketchContext(seed=7), route="refine", return_info=True
        )
        assert info["refine"]["converged"]
    sig = ProblemSignature(kind="ls", m=240, n=8, dtype="float32")
    d = choose_route(sig, store_view=load_entries(policy_env))
    assert d.route == "refine" and d.source == "profile"
    assert any("refine earned" in r for r in d.reasons)


def test_refine_never_earned_without_history(policy_env):
    """A matured entry with NO recorded refine runs keeps the sketch
    route — history is the only way in."""
    A, b = _ls_problem(dtype=np.float32, m=240, n=8)
    for _ in range(4):
        approximate_least_squares(A, b, SketchContext(seed=7))
    sig = ProblemSignature(kind="ls", m=240, n=8, dtype="float32")
    d = choose_route(sig, store_view=load_entries(policy_env))
    assert d.source == "profile" and d.route == "sketch"


def test_single_stagnation_retires_refine(policy_env):
    """choose_route on a crafted view: certified history earns the
    route; one recorded stagnation (or a guard blemish) retires it."""
    sig = ProblemSignature(kind="ls", m=240, n=8, dtype="float32")
    entry = {
        "runs": 5,
        "guard": {"fallback": 0, "resketch": 0},
        "cond": {"max": 3.0},
        "refine": {"ok": 4, "stagnate": 0, "iters": 20, "rung": "bf16+f32"},
    }
    view = {"entries": {sig.key: dict(entry)}}
    assert choose_route(sig, store_view=view).route == "refine"
    retired = dict(entry, refine=dict(entry["refine"], stagnate=1))
    view = {"entries": {sig.key: retired}}
    assert choose_route(sig, store_view=view).route == "sketch"
    unhealthy = dict(entry, guard={"fallback": 0, "resketch": 2})
    view = {"entries": {sig.key: unhealthy}}
    assert choose_route(sig, store_view=view).route != "refine"


# ---------------------------------------------------------------------------
# served cond-est


_SRV_RNG = np.random.default_rng(1234)
_SRV_A = _SRV_RNG.standard_normal((64, 5))


def _cond_server(max_coalesce, seed=42):
    srv = serve.Server(
        serve.ServeParams(
            max_coalesce=max_coalesce, warm_start=False, prime=False
        ),
        seed=seed,
    )
    srv.registry.register_system(
        "sys", _SRV_A, context=SketchContext(seed=9)
    )
    return srv


def test_served_cond_est_coalesced_equals_solo():
    solo_srv = _cond_server(1)
    solo_srv.start()
    solo = solo_srv.call({"op": "cond_est", "system": "sys"})
    solo_srv.stop()
    assert solo["ok"], solo
    rep = solo["result"]
    assert rep["system"] == "sys" and rep["n"] == 5
    assert rep["effective_rank"] == 5
    assert np.isfinite(rep["cond"]) and rep["cond"] >= 1.0
    assert rep["sigma_max"] >= rep["sigma_min"] > 0

    co_srv = _cond_server(8)
    futures = [
        co_srv.submit({"op": "cond_est", "system": "sys"}) for _ in range(6)
    ]
    co_srv.start()
    results = [f.result() for f in futures]
    co_srv.stop()
    for r in results:
        assert r["ok"]
        assert r["result"] == rep  # identical dict, coalesced or solo


def test_served_cond_est_unknown_system():
    srv = _cond_server(1)
    srv.start()
    r = srv.call({"op": "cond_est", "system": "nope"})
    srv.stop()
    assert not r["ok"]
    assert r["error"]["code"] == ex.InvalidParameters("x").code


# ---------------------------------------------------------------------------
# quasirandom sketch interchange


def test_qjlt_json_interchange_bitwise():
    from libskylark_tpu.sketch.base import create_sketch, from_json

    m, s = 256, 64
    A = jnp.asarray(
        np.random.default_rng(2).standard_normal((m, 12)).astype(np.float32)
    )
    S = create_sketch("QJLT", m, s, SketchContext(seed=17))
    SA = plans.apply(S, A, "columnwise")
    S2 = from_json(S.to_json())
    SA2 = plans.apply(S2, A, "columnwise")
    assert np.array_equal(np.asarray(SA), np.asarray(SA2))
    d = S.to_dict()
    assert d["leap"] == S.leap and d["skip"] == S.skip


def test_refine_rides_qjlt_sketch():
    A, b = _ls_problem(dtype=np.float32)
    X, info = refine_least_squares(
        A, b, SketchContext(seed=13), RefineParams(sketch_type="QJLT")
    )
    assert info["refine"]["converged"]
    xs = np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]
    r_exact = np.linalg.norm(np.asarray(A) @ xs - np.asarray(b))
    r_ref = float(jnp.linalg.norm(A @ X - b))
    assert r_ref <= r_exact * (1 + 1e-4)
