"""Multi-tenant QoS (ISSUE PR 18): weighted-fair lanes + quotas.

The load-bearing contracts:

- **Default tenants are preserved bitwise.**  Requests that never name a
  tenant ride the default lane, and a queue whose only lane IS the
  default short-circuits to the exact legacy FIFO — single-tenant
  deployments see zero behaviour change.
- **Deficit-weighted round-robin is fair at the queue.**  A flooding
  tenant gets its own lane drained at its weight's share; a polite
  tenant's entries are never stuck behind the flood.
- **Coalescing never crosses a tenant boundary** (isolation), but is
  unchanged WITHIN the picked tenant's lane (throughput).
- **Quotas shed 117, globally sheds stay 112/113.**  A tenant over its
  token bucket gets a structured ``QuotaExceededError`` envelope with a
  ``retry_after_ms`` backoff hint; other tenants are untouched.
- **Tenants are observable end to end**: stamped into trace envelopes,
  folded as ``serve.tenants`` in ``telemetry.snapshot()``, rendered by
  Prometheus exposition and the skylark-top tenant table.
"""

import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from libskylark_tpu import serve, telemetry
from libskylark_tpu.cli import top
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.serve.admission import AdmissionQueue, Entry
from libskylark_tpu.serve.qos import (
    DEFAULT_TENANT,
    LaneConfig,
    TenantQuotas,
    TokenBucket,
    tenant_of,
)
from libskylark_tpu.utils import exceptions as ex

pytestmark = pytest.mark.qos

M, N = 48, 6
_rng = np.random.default_rng(31)
A_LS = _rng.standard_normal((M, N))
B = _rng.standard_normal(M)


def _entry(i, tenant=DEFAULT_TENANT, key=None):
    e = Entry(
        {"op": "ls_solve", "system": "sys"}, Future(),
        key if key is not None else ("k", i), "ls_solve",
        payload=np.zeros(1),
    )
    e.tenant = tenant
    return e


def _server(**params):
    params.setdefault("warm_start", False)
    params.setdefault("prime", False)
    params.setdefault("cache", False)
    srv = serve.Server(serve.ServeParams(**params), seed=1)
    srv.registry.register_system(
        "sys", A_LS, context=SketchContext(seed=9),
        sketch_type="SJLT", sketch_size=32, capacity=M + 8,
    )
    return srv


# ---------------------------------------------------------------------------
# tenant keys and the default-lane FIFO guarantee


def test_tenant_of_reads_payload_field():
    assert tenant_of({"op": "ping"}) == DEFAULT_TENANT
    assert tenant_of(None) == DEFAULT_TENANT
    assert tenant_of({"op": "ping", "tenant": "acme"}) == "acme"
    assert tenant_of({"tenant": 7}) == "7"


def test_lone_default_lane_is_exact_fifo_with_coalescing():
    q = AdmissionQueue(16, lanes=LaneConfig(quantum=1))
    a, b, c = _entry(0, key=("k",)), _entry(1, key=("k",)), _entry(2)
    for e in (a, b, c):
        q.offer(e)
    # head + same-key riders, admission order — the legacy contract
    batch = q.take_batch(16)
    assert batch == [a, b]
    assert q.take_batch(16) == [c]
    assert q.depth_by_tenant() == {}
    q.close()
    assert q.take_batch(16) is None


def test_drr_serves_tenants_at_their_weights():
    q = AdmissionQueue(
        64, lanes=LaneConfig(quantum=1, weights={"a": 2.0, "b": 1.0})
    )
    # distinct keys: nothing coalesces, every take serves one entry
    for i in range(8):
        q.offer(_entry(i, tenant="a"))
    for i in range(8, 16):
        q.offer(_entry(i, tenant="b"))
    assert q.depth_by_tenant() == {"a": 8, "b": 8}
    picks = [q.take_batch(1)[0].tenant for _ in range(12)]
    # weight 2:1 → tenant a gets twice the service in every window
    assert picks.count("a") == 8 and picks.count("b") == 4
    # b was never starved: it appears within the first weight-round
    assert "b" in picks[:3]
    q.close()


def test_coalescing_never_crosses_tenants():
    q = AdmissionQueue(16, lanes=LaneConfig(quantum=1))
    a1, a2 = _entry(0, "a", key=("k",)), _entry(1, "a", key=("k",))
    b1 = _entry(2, "b", key=("k",))
    for e in (a1, a2, b1):
        q.offer(e)
    first = q.take_batch(16)
    second = q.take_batch(16)
    # same coalesce key, but the batches split on the tenant boundary;
    # within a tenant's lane the coalescing identity is unchanged
    assert first == [a1, a2] and second == [b1]
    q.close()


def test_admission_depth_cap_stays_global():
    q = AdmissionQueue(2, lanes=LaneConfig(quantum=1))
    q.offer(_entry(0, "a"))
    q.offer(_entry(1, "b"))
    with pytest.raises(ex.AdmissionError):  # code 112, across ALL lanes
        q.offer(_entry(2, "c"))
    q.close()


def test_depth_freed_as_batch_forms_during_coalesce_window():
    """Queue depth is released entry-by-entry as ``take_batch`` pops
    (REVIEW): an in-flight batch lingering in the coalesce window no
    longer counts against ``max_depth``, so a same-key arrival near
    capacity is admitted (and coalesced) instead of shed 112."""
    q = AdmissionQueue(1, lanes=LaneConfig(quantum=1))
    q.offer(_entry(0, key=("k",)))
    out = {}
    t = threading.Thread(
        target=lambda: out.update(batch=q.take_batch(4, window_s=0.5))
    )
    t.start()
    deadline = time.monotonic() + 2.0
    while True:
        try:
            q.offer(_entry(1, key=("k",)))
            break
        except ex.AdmissionError:
            # the taker has not popped the head yet — depth still held
            if time.monotonic() > deadline:
                t.join(timeout=5)
                pytest.fail("offer shed 112 for the whole linger window")
            time.sleep(0.01)
    t.join(timeout=5)
    q.close()
    # the admitted arrival coalesced into the lingering batch
    assert [e.request["op"] for e in out["batch"]] == ["ls_solve"] * 2


# ---------------------------------------------------------------------------
# token-bucket quotas: deterministic, per-tenant, code 117


def test_token_bucket_refills_on_injected_clock():
    now = [0.0]
    bucket = TokenBucket(rate=2.0, burst=2.0, clock=lambda: now[0])
    assert bucket.take() is None and bucket.take() is None
    retry = bucket.take()  # burst spent, no time has passed
    assert retry is not None and retry >= 1
    now[0] += 0.5  # one token accrues at 2 req/s
    assert bucket.take() is None


def test_tenant_quotas_shed_117_per_tenant_only():
    now = [0.0]
    quotas = TenantQuotas(
        quotas={"noisy": (1.0, 2.0)}, default_rps=0, clock=lambda: now[0]
    )
    quotas.admit("noisy")
    quotas.admit("noisy")
    with pytest.raises(ex.QuotaExceededError) as ei:
        quotas.admit("noisy")
    e = ei.value
    assert e.code == 117 and e.tenant == "noisy"
    assert e.rate == 1.0 and e.burst == 2.0 and e.retry_after_ms >= 1
    # other tenants (and the default) are unlimited — quotas are opt-in
    for _ in range(50):
        quotas.admit("polite")
        quotas.admit(DEFAULT_TENANT)
    now[0] += 1.0
    quotas.admit("noisy")  # a token accrued: admitted again


def test_quota_shed_envelope_roundtrip_through_server():
    srv = _server(tenant_quotas="noisy:1:2")
    # no worker: the first two requests sit in the queue; the third is
    # refused AT THE DOOR with the structured 117 envelope
    reqs = [
        serve.make_request("ls_solve", system="sys", b=B, tenant="noisy")
        for _ in range(3)
    ]
    futs = [srv.submit(r) for r in reqs]
    shed = futs[2].result(timeout=5)
    assert not shed["ok"]
    err = shed["error"]
    assert err["code"] == 117 and err["tenant"] == "noisy"
    assert err["rate"] == 1.0 and err["burst"] == 2.0
    assert err["retry_after_ms"] >= 1
    assert any(
        ev["kind"] == "quota_shed" for ev in shed["trace"]["events"]
    )
    with pytest.raises(ex.QuotaExceededError):
        serve.raise_for_error(shed)
    # the default tenant rides free past the noisy tenant's quota
    ok_fut = srv.submit(serve.make_request("ls_solve", system="sys", b=B))
    assert not ok_fut.done()  # admitted (queued), not shed
    assert srv.queue.depth_by_tenant() == {"noisy": 2, DEFAULT_TENANT: 1}
    srv.stop()  # resolves the queued futures with shutdown envelopes


# ---------------------------------------------------------------------------
# observability: trace stamp, snapshot fold, exposition, top


def test_tenant_stamped_and_folded_into_telemetry(monkeypatch):
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.REGISTRY.reset()
    try:
        srv = _server(cache=True).start()
        try:
            r1 = srv.call(
                op="ls_solve", system="sys", b=B, tenant="acme"
            )
            r2 = srv.call(
                op="ls_solve", system="sys", b=B, tenant="acme"
            )
            srv.call(op="ls_solve", system="sys", b=B)
        finally:
            srv.stop()
        assert r1["trace"]["tenant"] == "acme"
        assert r2["trace"].get("cache_hit") is True
        snap = telemetry.snapshot()
        tenants = snap["serve"]["tenants"]
        assert tenants["acme"]["requests"] == 2
        assert tenants["acme"]["ok"] == 1  # the dispatch
        assert tenants["acme"]["cache_hits"] == 1  # the dict lookup
        # the cache is tenant-agnostic by design (results are
        # deterministic): the default tenant's identical payload hits too
        assert tenants[DEFAULT_TENANT]["requests"] == 1
        assert tenants[DEFAULT_TENANT]["cache_hits"] == 1
        # the flat serve group keeps its pre-QoS key set: per-tenant
        # counters fold ONLY nested
        assert not any(
            k.startswith("tenant.") for k in snap["serve"]
        )
        assert snap["serve"]["cache_hit_rate"] is not None
        text = telemetry.prometheus_text()
        # per-tenant counters export as ONE family with a tenant label
        # (PR 20: distinct raw tenants must stay distinct on the wire)
        assert (
            'skylark_serve_tenant_requests_total{tenant="acme"} 2' in text
        )
        assert "skylark_serve_cache_hit_total 2" in text
    finally:
        telemetry.REGISTRY.reset()


def test_tenant_metric_label_cardinality_is_bounded(monkeypatch):
    """Counter-name cardinality cap (REVIEW): the tenant key is client-
    controlled (header/payload), so an attacker cycling tenant names
    must NOT mint unbounded ``serve.tenant.*`` counters.  Configured
    tenants (weights/quotas) always keep their label; past the
    ``SKYLARK_QOS_TENANT_METRICS_MAX`` budget the rest fold into the
    ``other`` bucket — while lanes, quotas, and trace envelopes keep
    the raw key."""
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    monkeypatch.setenv("SKYLARK_QOS_TENANT_METRICS_MAX", "2")
    telemetry.REGISTRY.reset()
    try:
        srv = _server(tenant_quotas="vip:100:200")
        # no worker started: requests queue, door-side counters mint
        futs = [
            srv.submit(serve.make_request(
                "ls_solve", system="sys", b=B, tenant=f"mallory-{i}"
            ))
            for i in range(6)
        ]
        futs.append(srv.submit(serve.make_request(
            "ls_solve", system="sys", b=B, tenant="vip"
        )))
        tenants = telemetry.snapshot()["serve"]["tenants"]
        assert tenants["vip"]["requests"] == 1  # configured: labelled
        assert tenants["other"]["requests"] == 6  # the flood folds
        assert not any(t.startswith("mallory") for t in tenants)
        # the QoS planes still see every raw tenant — only metric
        # labels are bounded
        depth = srv.queue.depth_by_tenant()
        assert sum(1 for t in depth if t.startswith("mallory")) == 6
        srv.stop()
        assert all(f.done() for f in futs)
    finally:
        telemetry.REGISTRY.reset()


def test_top_renders_tenant_table_and_cache_line():
    stats = {
        "queue_depth": 0,
        "latency": {},
        "counters": {
            "requests": 5, "ok": 4,
            "cache.hit": 2, "cache.miss": 1,
            "tenant.acme.requests": 3, "tenant.acme.ok": 2,
            "tenant.acme.cache_hits": 1, "tenant.acme.shed_quota": 1,
        },
    }
    health = {"backend": "cpu", "registry": {}, "primed": [],
              "worker_alive": True}
    text = "\n".join(top._serve_lines(stats, health, {}))
    assert "cache hits 2  misses 1" in text
    assert "shed q/a/d" in text  # the tenant table header
    assert "acme" in text and "1/0/0" in text
    # tenantless, cacheless stats render no extra lines (legacy shape)
    bare = "\n".join(
        top._serve_lines(
            {"queue_depth": 0, "latency": {}, "counters": {"requests": 1}},
            health, {},
        )
    )
    assert "tenant" not in bare and "cache hits" not in bare


def test_http_header_maps_to_tenant_field():
    import json
    import threading
    import urllib.request

    srv = _server(cache=False).start()
    httpd = serve.serve_http(srv, port=0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        host, port = httpd.server_address[:2]
        body = serve.encode(
            serve.make_request("ls_solve", system="sys", b=B.tolist())
        ).encode()
        req = urllib.request.Request(
            f"http://{host}:{port}/", data=body,
            headers={"Content-Type": "application/json",
                     "X-Skylark-Tenant": "acme"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            resp = json.loads(r.read())
        assert resp["ok"] and resp["trace"]["tenant"] == "acme"
        # an explicit payload field wins over the header
        body2 = json.dumps(
            dict(serve.make_request(
                "ls_solve", system="sys", b=B.tolist()
            ), tenant="explicit")
        ).encode()
        req2 = urllib.request.Request(
            f"http://{host}:{port}/", data=body2,
            headers={"Content-Type": "application/json",
                     "X-Skylark-Tenant": "acme"},
        )
        with urllib.request.urlopen(req2, timeout=10) as r:
            resp2 = json.loads(r.read())
        assert resp2["trace"]["tenant"] == "explicit"
    finally:
        httpd.shutdown()
        srv.stop()


# ---------------------------------------------------------------------------
# marker contract


@pytest.mark.qos
def test_qos_marker_registered_tier1():
    """Marker contract (ISSUE PR 18): the ``qos`` marker must stay a
    registered tier-1 mark with a hard per-test alarm — QoS tests run
    live servers under multi-tenant load, which could otherwise wedge
    the tier-1 run.  Static over conftest so dropping the mark (or
    demoting it to slow) fails here."""
    import pathlib

    src = (pathlib.Path(__file__).parent / "conftest.py").read_text()
    assert '"qos": QOS_TIMEOUT_S' in src, (
        "the qos marker lost its _TIMEOUT_MARKS alarm entry"
    )
    assert "QOS_TIMEOUT_S = 120" in src
    assert '"markers",\n        "qos:' in src, (
        "the qos marker is no longer registered via addinivalue_line"
    )
