"""Child process for the multi-process elastic streaming tests.

Usage::

    python tests/_elastic_child.py <proc_id> <num_procs> <port> \
        <checkpoint_root> <out_dir> <resume>

One rank of a ``jax.distributed`` world running the distributed
streaming sketch-and-solve (``distributed_sketch_least_squares``) over
a deterministic synthetic problem.  The whole world streams the SAME
global source; each rank folds only its ``RowPartition`` share and the
psum merge makes ``x`` identical everywhere.  On success the rank saves
``x-<rank>.npy`` + ``info-<rank>.json`` into ``out_dir`` and prints
``ELASTIC-OK``.

Fault injection (the kill-one-rank scenario): when
``ELASTIC_KILL_RANK`` matches this rank, a ``FaultPlan`` subclass
SIGKILLs the process right after checkpoint chunk
``ELASTIC_KILL_AFTER_CHUNK`` commits — a real uncatchable death
mid-stream, not an exception.  The parent restarts the world with
``resume=1`` and checks bit-identity against an uninterrupted run.
"""

from __future__ import annotations

import json
import os
import signal
import sys

NROWS, NCOLS, BATCH_ROWS, S_SIZE = 96, 5, 4, 24


def main() -> int:
    proc_id, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    root, out_dir, resume = sys.argv[4], sys.argv[5], sys.argv[6] == "1"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=proc_id,
        initialization_timeout=60,
    )

    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu import SketchContext
    from libskylark_tpu.resilient import FaultPlan
    from libskylark_tpu.sketch.dense import JLT
    from libskylark_tpu.streaming import ElasticParams, RowPartition
    from libskylark_tpu.streaming.elastic import (
        distributed_sketch_least_squares,
    )

    # Deterministic synthetic problem — every rank (and every restart)
    # regenerates the identical stream.
    rng = np.random.default_rng(5)
    A = rng.standard_normal((NROWS, NCOLS))
    b = rng.standard_normal(NROWS)
    blocks = [
        (jnp.asarray(A[lo : lo + BATCH_ROWS]),
         jnp.asarray(b[lo : lo + BATCH_ROWS]))
        for lo in range(0, NROWS, BATCH_ROWS)
    ]

    def factory(start: int):
        return iter(blocks[start:])

    part = RowPartition(
        nrows=NROWS, batch_rows=BATCH_ROWS, world_size=nprocs
    )
    S = JLT(NROWS, S_SIZE, SketchContext(seed=13))

    kill_rank = int(os.environ.get("ELASTIC_KILL_RANK", "-1"))
    kill_after = int(os.environ.get("ELASTIC_KILL_AFTER_CHUNK", "-1"))

    class KillPlan(FaultPlan):
        """SIGKILL this process right after a chunk commit — the commit
        is durable (fsynced file + directory), the death is real."""

        def after_commit(self, chunk: int) -> None:
            if chunk == kill_after:
                sys.stdout.flush()
                os.kill(os.getpid(), signal.SIGKILL)

    plan = KillPlan() if (proc_id == kill_rank and kill_after >= 0) else None
    params = ElasticParams(
        checkpoint_dir=root, checkpoint_every=1, resume=resume, prefetch=0
    )
    x, info = distributed_sketch_least_squares(
        factory, S, ncols=NCOLS, partition=part, params=params,
        fault_plan=plan,
    )
    np.save(os.path.join(out_dir, f"x-{proc_id}.npy"), np.asarray(x))
    with open(
        os.path.join(out_dir, f"info-{proc_id}.json"), "w", encoding="utf-8"
    ) as fh:
        json.dump(
            {k: info[k] for k in
             ("rows", "batches", "local_batches", "world_size", "rank")},
            fh,
        )
    print("ELASTIC-OK", flush=True)
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
