"""Child process for the multi-process elastic streaming tests.

Usage::

    python tests/_elastic_child.py <proc_id> <num_procs> <port> \
        <checkpoint_root> <out_dir> <resume>

One rank of a ``jax.distributed`` world running the distributed
streaming sketch-and-solve (``distributed_sketch_least_squares``) over
a deterministic synthetic problem.  The whole world streams the SAME
global source; each rank folds only its ``RowPartition`` share and the
psum merge makes ``x`` identical everywhere.  On success the rank saves
``x-<rank>.npy`` + ``info-<rank>.json`` into ``out_dir`` and prints
``ELASTIC-OK``.

Fault injection, all driven by environment variables so the parent
composes scenarios without new scripts:

- ``ELASTIC_KILL_RANK`` / ``ELASTIC_KILL_AFTER_CHUNK``: SIGKILL that
  rank right after the given checkpoint chunk commits — a real
  uncatchable death mid-stream, not an exception.
- ``ELASTIC_FAULT_RANK`` + ``ELASTIC_DIE_AT_BATCH`` /
  ``ELASTIC_SLOW_AT_BATCH`` + ``ELASTIC_SLOW_SECONDS`` /
  ``ELASTIC_TORN_LEDGER``: a :class:`HostFaultPlan` on that rank —
  rank death before a batch (optionally tearing the ledger tail first)
  or a straggler sleep that drives peers into their collective
  deadline.
- ``ELASTIC_RESUME_POLICY``: ``strict`` (default) or ``repartition`` —
  the resumed world may be a DIFFERENT size than the interrupted one.
- ``ELASTIC_COLLECTIVE_TIMEOUT_S``: deadline-bound the handshake and
  psum merges; on timeout the rank prints ``ELASTIC-TIMEOUT`` with the
  straggler list and exits with code 110 (111 for a stale epoch)
  instead of hanging the parent.
- ``ELASTIC_EXACT=1``: integer-valued data + a CWT sketch (±1 values),
  so every fold is exact integer arithmetic in float64 and a
  repartitioned resume must match an uninterrupted run at the NEW
  world size bit-for-bit.
- ``ELASTIC_TRAIN=1``: run the distributed BlockADMM TRAINING scenario
  instead (``DistributedBlockADMMTrainer`` over the same world): each
  rank streams its feature blocks, trains in lockstep (one consensus
  psum per outer iteration), and saves its model ``W`` as
  ``x-<rank>.npy`` — same artifact names, so the parent's kill/resume
  bit-identity machinery drives both scenarios.  The
  ``ELASTIC_KILL_*`` knobs kill mid-STREAM (feature pass);
  ``ELASTIC_TRAIN_KILL_AFTER_CHUNK`` kills after that ADMM checkpoint
  chunk commits instead (mid-TRAINING).
"""

from __future__ import annotations

import json
import os
import signal
import sys

NROWS, NCOLS, BATCH_ROWS, S_SIZE = 96, 5, 4, 24


def main() -> int:
    proc_id, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    root, out_dir, resume = sys.argv[4], sys.argv[5], sys.argv[6] == "1"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=proc_id,
        initialization_timeout=60,
    )

    import jax.numpy as jnp
    import numpy as np

    from libskylark_tpu import SketchContext
    from libskylark_tpu.resilient import FaultPlan, HostFaultPlan
    from libskylark_tpu.sketch.dense import JLT
    from libskylark_tpu.sketch.hash import CWT
    from libskylark_tpu.streaming import ElasticParams, RowPartition
    from libskylark_tpu.streaming.elastic import (
        distributed_sketch_least_squares,
    )
    from libskylark_tpu.utils.exceptions import (
        CollectiveTimeoutError,
        StaleEpochError,
    )

    # Deterministic synthetic problem — every rank (and every restart)
    # regenerates the identical stream.
    rng = np.random.default_rng(5)
    exact = os.environ.get("ELASTIC_EXACT") == "1"
    if exact:
        # integer data + CWT: exact f64 sums, bitwise-stable under any
        # summation regrouping (the repartition bit-identity lock)
        A = rng.integers(-9, 10, size=(NROWS, NCOLS)).astype(np.float64)
        b = rng.integers(-9, 10, size=NROWS).astype(np.float64)
        S = CWT(NROWS, S_SIZE, SketchContext(seed=13))
    else:
        A = rng.standard_normal((NROWS, NCOLS))
        b = rng.standard_normal(NROWS)
        S = JLT(NROWS, S_SIZE, SketchContext(seed=13))
    blocks = [
        (jnp.asarray(A[lo : lo + BATCH_ROWS]),
         jnp.asarray(b[lo : lo + BATCH_ROWS]))
        for lo in range(0, NROWS, BATCH_ROWS)
    ]

    def factory(start: int):
        return iter(blocks[start:])

    part = RowPartition(
        nrows=NROWS, batch_rows=BATCH_ROWS, world_size=nprocs
    )

    kill_rank = int(os.environ.get("ELASTIC_KILL_RANK", "-1"))
    kill_after = int(os.environ.get("ELASTIC_KILL_AFTER_CHUNK", "-1"))

    class KillPlan(FaultPlan):
        """SIGKILL this process right after a chunk commit — the commit
        is durable (fsynced file + directory), the death is real."""

        def after_commit(self, chunk: int) -> None:
            if chunk == kill_after:
                sys.stdout.flush()
                os.kill(os.getpid(), signal.SIGKILL)

    plan = KillPlan() if (proc_id == kill_rank and kill_after >= 0) else None
    fault_rank = int(os.environ.get("ELASTIC_FAULT_RANK", "-1"))
    if proc_id == fault_rank and plan is None:
        host_knobs = {}
        if os.environ.get("ELASTIC_DIE_AT_BATCH"):
            host_knobs["die_at_batch"] = int(
                os.environ["ELASTIC_DIE_AT_BATCH"]
            )
        if os.environ.get("ELASTIC_SLOW_AT_BATCH"):
            host_knobs["slow_at_batch"] = int(
                os.environ["ELASTIC_SLOW_AT_BATCH"]
            )
            host_knobs["slow_seconds"] = float(
                os.environ.get("ELASTIC_SLOW_SECONDS", "0")
            )
        if os.environ.get("ELASTIC_TORN_LEDGER") == "1":
            host_knobs["torn_ledger"] = True
        if host_knobs:
            plan = HostFaultPlan(**host_knobs)

    timeout_env = os.environ.get("ELASTIC_COLLECTIVE_TIMEOUT_S")
    params = ElasticParams(
        checkpoint_dir=root, checkpoint_every=1, resume=resume, prefetch=0,
        resume_policy=os.environ.get("ELASTIC_RESUME_POLICY", "strict"),
        collective_timeout_s=float(timeout_env) if timeout_env else None,
    )
    if os.environ.get("ELASTIC_TRAIN") == "1":
        from libskylark_tpu.ml import GaussianKernel
        from libskylark_tpu.ml.admm import ADMMParams
        from libskylark_tpu.ml.distributed import DistributedBlockADMMTrainer

        # Regression targets: no global class set to thread through.
        y = rng.standard_normal(NROWS)
        blocks_t = [
            (jnp.asarray(A[lo : lo + BATCH_ROWS]),
             jnp.asarray(y[lo : lo + BATCH_ROWS]))
            for lo in range(0, NROWS, BATCH_ROWS)
        ]

        def train_factory(start: int):
            return iter(blocks_t[start:])

        kern = GaussianKernel(NCOLS, 2.0)
        ctx = SketchContext(seed=17)
        maps = [kern.create_rft(16, "regular", ctx) for _ in range(2)]
        # data_partitions=4 keeps every rank boundary on a partition
        # boundary for worlds 2 and 4 (96 rows -> ni=24; rank shares of
        # 48 or 24 rows are whole partitions).
        trainer = DistributedBlockADMMTrainer(
            "squared", "l2", maps,
            ADMMParams(rho=1.0, lam=0.01, maxiter=6, data_partitions=4),
            params,
        )
        train_kill = int(os.environ.get("ELASTIC_TRAIN_KILL_AFTER_CHUNK", "-1"))

        class TrainKillPlan(FaultPlan):
            def after_commit(self, chunk: int) -> None:
                if chunk == train_kill:
                    sys.stdout.flush()
                    os.kill(os.getpid(), signal.SIGKILL)

        train_plan = (
            TrainKillPlan()
            if (proc_id == kill_rank and train_kill >= 0)
            else None
        )
        try:
            model, info = trainer.train(
                train_factory, part, regression=True, fault_plan=plan,
                train_fault_plan=train_plan,
            )
        except CollectiveTimeoutError as e:
            print(
                f"ELASTIC-TIMEOUT phase={e.phase} "
                f"stragglers={e.stragglers}",
                flush=True,
            )
            os._exit(110)
        except StaleEpochError:
            print("ELASTIC-STALE-EPOCH", flush=True)
            os._exit(111)
        np.save(
            os.path.join(out_dir, f"x-{proc_id}.npy"), np.asarray(model.W)
        )
        keys = ("rows", "batches", "local_batches", "world_size", "rank",
                "iters", "consensus_residual", "precision")
        dump = {k: info[k] for k in keys}
        if info.get("replay") is not None:
            dump["replay"] = info["replay"]
        with open(
            os.path.join(out_dir, f"info-{proc_id}.json"), "w",
            encoding="utf-8",
        ) as fh:
            json.dump(dump, fh)
        print("ELASTIC-OK", flush=True)
        jax.distributed.shutdown()
        return 0
    try:
        x, info = distributed_sketch_least_squares(
            factory, S, ncols=NCOLS, partition=part, params=params,
            fault_plan=plan,
        )
    except CollectiveTimeoutError as e:
        print(
            f"ELASTIC-TIMEOUT phase={e.phase} "
            f"stragglers={e.stragglers}",
            flush=True,
        )
        # The blocked collective still owns a daemon thread inside the
        # runtime; a clean interpreter shutdown would hang on it.
        os._exit(110)
    except StaleEpochError:
        print("ELASTIC-STALE-EPOCH", flush=True)
        os._exit(111)
    np.save(os.path.join(out_dir, f"x-{proc_id}.npy"), np.asarray(x))
    keys = ("rows", "batches", "local_batches", "world_size", "rank")
    dump = {k: info[k] for k in keys}
    if info.get("replay") is not None:
        dump["replay"] = info["replay"]
    with open(
        os.path.join(out_dir, f"info-{proc_id}.json"), "w", encoding="utf-8"
    ) as fh:
        json.dump(dump, fh)
    print("ELASTIC-OK", flush=True)
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
