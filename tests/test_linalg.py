"""Tests for linalg: randomized SVD + least squares.

Models the reference's test strategy (SURVEY §4):
- SVD product property test ≙ ``equal_svd_product`` (``tests/unit/
  test_utils.hpp:55-100``, ``SVDElementalTest.cpp``).
- Statistical bound for sketched problems ≙ ``tests/regression/svd_test.py``.
- Sharded-vs-local equality ≙ ``DenseSketchApplyElementalTest.cpp:52-102``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import SketchContext
from libskylark_tpu.linalg import (
    LeastSquaresParams,
    SVDParams,
    approximate_least_squares,
    approximate_svd,
    approximate_symmetric_svd,
    exact_least_squares,
    power_iteration,
    streaming_approximate_svd,
    synthetic_lowrank_blocks,
)
from libskylark_tpu.parallel import default_mesh, shard_rows


def low_rank(rng, m, n, k, noise=0.0):
    A = rng.standard_normal((m, k)) @ rng.standard_normal((k, n))
    if noise:
        A = A + noise * rng.standard_normal((m, n))
    return jnp.asarray(A)


class TestApproximateSVD:
    def test_exact_on_low_rank(self, rng):
        A = low_rank(rng, 120, 60, 5)
        U, s, V = approximate_svd(A, 5, SketchContext(seed=1))
        rec = U @ jnp.diag(s) @ V.T
        assert np.linalg.norm(rec - A) / np.linalg.norm(A) < 1e-8

    def test_orthonormal_factors(self, rng):
        A = low_rank(rng, 100, 50, 8, noise=0.01)
        U, s, V = approximate_svd(
            A, 8, SketchContext(seed=2), SVDParams(num_iterations=2)
        )
        np.testing.assert_allclose(U.T @ U, np.eye(8), atol=1e-10)
        np.testing.assert_allclose(V.T @ V, np.eye(8), atol=1e-10)
        assert np.all(np.diff(np.asarray(s)) <= 1e-12)

    def test_singular_value_accuracy_statistical(self, rng):
        # ≙ tests/regression/svd_test.py:24-80 — repeats + relative bound.
        A = jnp.asarray(rng.standard_normal((300, 40)))
        s_true = np.linalg.svd(np.asarray(A), compute_uv=False)[:10]
        ok = 0
        for rep in range(5):
            _, s, _ = approximate_svd(
                A,
                10,
                SketchContext(seed=100 + rep),
                SVDParams(num_iterations=3, oversampling_ratio=3),
            )
            if np.all(np.abs(np.asarray(s) - s_true) <= 0.5 * s_true):
                ok += 1
        assert ok >= 1

    def test_power_iteration_improves(self, rng):
        A = jnp.asarray(
            np.linalg.qr(rng.standard_normal((200, 200)))[0]
            @ np.diag(np.logspace(0, -6, 200))
            @ np.linalg.qr(rng.standard_normal((200, 200)))[0]
        )
        errs = []
        for q in (0, 3):
            U, s, V = approximate_svd(
                A, 10, SketchContext(seed=7), SVDParams(num_iterations=q)
            )
            errs.append(
                np.linalg.norm(U @ jnp.diag(s) @ V.T - A, 2)
            )
        assert errs[1] <= errs[0] + 1e-12

    def test_sharded_matches_local(self, rng):
        A = low_rank(rng, 128, 32, 4, noise=0.001)
        U0, s0, V0 = approximate_svd(A, 4, SketchContext(seed=3))
        mesh = default_mesh()
        As = shard_rows(A, mesh)
        U1, s1, V1 = jax.jit(
            lambda X: approximate_svd(X, 4, SketchContext(seed=3))
        )(As)
        np.testing.assert_allclose(
            np.asarray(s0), np.asarray(s1), rtol=1e-8, atol=1e-10
        )
        rec0 = U0 @ jnp.diag(s0) @ V0.T
        rec1 = U1 @ jnp.diag(s1) @ V1.T
        np.testing.assert_allclose(
            np.asarray(rec0), np.asarray(rec1), rtol=1e-6, atol=1e-8
        )

    def test_jittable(self, rng):
        A = low_rank(rng, 64, 32, 4)
        f = jax.jit(lambda X: approximate_svd(X, 4, SketchContext(seed=5)))
        U, s, V = f(A)
        assert U.shape == (64, 4) and s.shape == (4,) and V.shape == (32, 4)


@pytest.mark.slow
class TestStreamingSVD:
    """Matrix-free row-streamed randomized SVD vs materialized oracles."""

    def _materialize(self, block_fn, m, n, block_rows):
        return np.vstack(
            [np.asarray(block_fn(i, block_rows)) for i in range(0, m, block_rows)]
        )

    def test_exact_on_low_rank(self):
        ctx = SketchContext(seed=31)
        m, n, r = 256, 48, 5
        block_fn = synthetic_lowrank_blocks(ctx, m, n, r, noise=0.0, decay=0.5)
        A = self._materialize(block_fn, m, n, 64)
        U, s, V = streaming_approximate_svd(
            block_fn, (m, n), r, ctx, block_rows=64, materialize_u=True
        )
        rec = np.asarray(U) @ np.diag(np.asarray(s)) @ np.asarray(V).T
        assert np.linalg.norm(rec - A) / np.linalg.norm(A) < 1e-4
        s_true = np.linalg.svd(A, compute_uv=False)[:r]
        np.testing.assert_allclose(np.asarray(s), s_true, rtol=1e-4)

    def test_noisy_singular_values_statistical(self):
        # ≙ tests/regression/svd_test.py bounds, streamed.
        ctx = SketchContext(seed=33)
        m, n, r = 512, 64, 8
        block_fn = synthetic_lowrank_blocks(ctx, m, n, r, noise=0.05, decay=0.8)
        A = self._materialize(block_fn, m, n, 128)
        s_true = np.linalg.svd(A, compute_uv=False)[:r]
        _, s, _ = streaming_approximate_svd(
            block_fn, (m, n), r, ctx,
            SVDParams(num_iterations=3, oversampling_ratio=3),
            block_rows=128,
        )
        assert np.all(np.abs(np.asarray(s) - s_true) <= 0.5 * s_true)

    def test_u_block_matches_materialized(self):
        ctx = SketchContext(seed=35)
        m, n, r = 128, 32, 4
        block_fn = synthetic_lowrank_blocks(ctx, m, n, r, noise=0.01)
        ctx2 = SketchContext(seed=35)
        block_fn2 = synthetic_lowrank_blocks(ctx2, m, n, r, noise=0.01)
        u_block, s1, V1 = streaming_approximate_svd(
            block_fn, (m, n), r, ctx, block_rows=32
        )
        U, s2, V2 = streaming_approximate_svd(
            block_fn2, (m, n), r, ctx2, block_rows=32, materialize_u=True
        )
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-6)
        got = np.vstack([np.asarray(u_block(i)) for i in range(4)])
        np.testing.assert_allclose(got, np.asarray(U), rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(
            got.T @ got, np.eye(r), atol=1e-3
        )

    def test_validation(self):
        ctx = SketchContext(seed=37)
        block_fn = synthetic_lowrank_blocks(ctx, 64, 16, 2)
        with pytest.raises(ValueError, match="divisible"):
            streaming_approximate_svd(block_fn, (64, 16), 2, ctx, block_rows=48)
        with pytest.raises(ValueError, match="rank"):
            streaming_approximate_svd(block_fn, (64, 16), 20, ctx, block_rows=32)


class TestSymmetricSVD:
    def test_symmetric_low_rank(self, rng):
        n, k = 80, 6
        Q = np.linalg.qr(rng.standard_normal((n, k)))[0]
        lam = np.array([5.0, -4.0, 3.0, 2.0, -1.5, 1.0])
        A = jnp.asarray(Q @ np.diag(lam) @ Q.T)
        V, lam_hat = approximate_symmetric_svd(
            A, k, SketchContext(seed=9), SVDParams(num_iterations=2)
        )
        rec = V @ jnp.diag(lam_hat) @ V.T
        assert np.linalg.norm(rec - A) / np.linalg.norm(A) < 1e-8
        np.testing.assert_allclose(
            np.sort(np.abs(np.asarray(lam_hat)))[::-1],
            np.sort(np.abs(lam))[::-1],
            rtol=1e-8,
        )


class TestExactLeastSquares:
    @pytest.mark.parametrize("alg", ["qr", "sne", "ne", "svd"])
    def test_matches_numpy(self, rng, alg):
        A = jnp.asarray(rng.standard_normal((60, 12)))
        b = jnp.asarray(rng.standard_normal(60))
        x_ref = np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]
        x = exact_least_squares(A, b, alg=alg)
        np.testing.assert_allclose(np.asarray(x), x_ref, rtol=1e-8, atol=1e-10)

    def test_multiple_rhs(self, rng):
        A = jnp.asarray(rng.standard_normal((40, 8)))
        B = jnp.asarray(rng.standard_normal((40, 3)))
        X = exact_least_squares(A, B)
        X_ref = np.linalg.lstsq(np.asarray(A), np.asarray(B), rcond=None)[0]
        np.testing.assert_allclose(np.asarray(X), X_ref, rtol=1e-8, atol=1e-10)


class TestApproximateLeastSquares:
    def test_residual_near_optimal_statistical(self, rng):
        # Sketch-and-solve guarantee: residual within (1+eps) of optimal.
        A = jnp.asarray(rng.standard_normal((2000, 20)))
        x_true = rng.standard_normal(20)
        b = jnp.asarray(np.asarray(A) @ x_true + 0.1 * rng.standard_normal(2000))
        r_opt = np.linalg.norm(
            np.asarray(A)
            @ np.linalg.lstsq(np.asarray(A), np.asarray(b), rcond=None)[0]
            - np.asarray(b)
        )
        ok = 0
        for rep in range(5):
            x = approximate_least_squares(A, b, SketchContext(seed=rep))
            r = np.linalg.norm(np.asarray(A @ x) - np.asarray(b))
            if r <= 1.5 * r_opt:
                ok += 1
        assert ok >= 3

    @pytest.mark.slow
    @pytest.mark.parametrize("sketch_type", ["JLT", "CWT"])
    def test_sketch_types(self, rng, sketch_type):
        A = jnp.asarray(rng.standard_normal((1000, 10)))
        b = jnp.asarray(rng.standard_normal(1000))
        x = approximate_least_squares(
            A,
            b,
            SketchContext(seed=4),
            LeastSquaresParams(sketch_type=sketch_type, sketch_size=200),
        )
        assert x.shape == (10,)
        assert np.all(np.isfinite(np.asarray(x)))


class TestCLI:
    @pytest.mark.slow
    def test_svd_cli_profile(self, tmp_path, monkeypatch):
        from libskylark_tpu.cli.svd import main

        monkeypatch.chdir(tmp_path)
        rc = main(
            ["--profile", "80", "40", "--rank", "4", "--prefix", "t", "--x64"]
        )
        assert rc == 0
        U = np.load(tmp_path / "t.U.npy")
        s = np.load(tmp_path / "t.S.npy")
        V = np.load(tmp_path / "t.V.npy")
        assert U.shape == (80, 4) and s.shape == (4,) and V.shape == (40, 4)

    def test_svd_cli_libsvm(self, tmp_path, rng):
        from libskylark_tpu.cli.svd import main
        from libskylark_tpu.io import write_libsvm

        X = rng.standard_normal((30, 10))
        write_libsvm(tmp_path / "d.libsvm", X, np.ones(30))
        rc = main(
            [
                str(tmp_path / "d.libsvm"),
                "--rank",
                "3",
                "--prefix",
                str(tmp_path / "o"),
            ]
        )
        assert rc == 0
        assert np.load(tmp_path / "o.S.npy").shape == (3,)

    @pytest.mark.slow
    def test_svd_cli_hdf5(self, tmp_path, rng):
        """HDF5 input parity (≙ skylark_svd's HDF5 role, VERDICT item 6)."""
        from libskylark_tpu.cli.svd import main
        from libskylark_tpu.io import write_hdf5

        X = rng.standard_normal((40, 12))
        write_hdf5(tmp_path / "d.h5", X, np.ones(40))
        rc = main(
            [str(tmp_path / "d.h5"), "--rank", "3",
             "--prefix", str(tmp_path / "h")]
        )
        assert rc == 0
        s = np.load(tmp_path / "h.S.npy")
        s_ref = np.linalg.svd(X, compute_uv=False)[:3]
        np.testing.assert_allclose(s, s_ref, rtol=0.5)

    @pytest.mark.slow
    def test_svd_cli_arclist(self, tmp_path, rng):
        """Arc-list input ≙ ReadArcList (skylark_svd.cpp:169-171): SVD of
        the graph adjacency."""
        from libskylark_tpu.cli.svd import main

        lines = ["# comment"]
        edges = {(int(a), int(b)) for a, b in rng.integers(0, 20, (60, 2))
                 if a != b}
        lines += [f"{a} {b}" for a, b in sorted(edges)]
        (tmp_path / "g.txt").write_text("\n".join(lines) + "\n")
        rc = main(
            [str(tmp_path / "g.txt"), "--filetype", "arclist", "--rank", "3",
             "--prefix", str(tmp_path / "g")]
        )
        assert rc == 0
        U = np.load(tmp_path / "g.U.npy")
        assert U.shape[1] == 3 and np.isfinite(U).all()

    @pytest.mark.slow
    def test_svd_cli_ascii_output(self, tmp_path, rng):
        """--ascii writes the reference's El::Write convention:
        prefix.U/.S/.V plain-text (skylark_svd.cpp:110-112)."""
        from libskylark_tpu.cli.svd import main

        X = rng.standard_normal((25, 8))
        np.save(tmp_path / "a.npy", X)
        rc = main(
            [str(tmp_path / "a.npy"), "--rank", "2", "--ascii",
             "--prefix", str(tmp_path / "a"), "--x64"]
        )
        assert rc == 0
        U = np.loadtxt(tmp_path / "a.U")
        s = np.loadtxt(tmp_path / "a.S")
        V = np.loadtxt(tmp_path / "a.V")
        assert U.shape == (25, 2) and s.shape == (2,) and V.shape == (8, 2)
        rec = U @ np.diag(s) @ V.T
        # Rank-2 truncation of a random matrix: just check the pieces
        # compose finitely and s is descending.
        assert np.isfinite(rec).all() and s[0] >= s[1]

    def test_svd_cli_symmetric(self, tmp_path, rng):
        """--symmetric ≙ execute_sym: eigendecomposition, writes S/V only."""
        from libskylark_tpu.cli.svd import main

        B = rng.standard_normal((15, 6))
        A = B @ B.T  # PSD, rank 6
        np.save(tmp_path / "s.npy", A)
        rc = main(
            [str(tmp_path / "s.npy"), "--rank", "4", "--symmetric",
             "--prefix", str(tmp_path / "s"), "--x64"]
        )
        assert rc == 0
        lam = np.load(tmp_path / "s.S.npy")
        V = np.load(tmp_path / "s.V.npy")
        assert not (tmp_path / "s.U.npy").exists()
        lam_ref = np.linalg.eigvalsh(A)[::-1][:4]
        np.testing.assert_allclose(np.sort(lam)[::-1], lam_ref, rtol=0.2)
        assert V.shape == (15, 4)
