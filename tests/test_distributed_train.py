"""Distributed kernel-machine training tests (``train`` marker — tier-1,
per-test timeout via conftest).

The load-bearing guarantees of ``ml/distributed.py``:

- world=1 distributed training is BIT-FOR-BIT identical to the
  in-process ``BlockADMMSolver.train`` (streamed rowwise-bucketed
  feature materialization == ``_prepare``'s columnwise apply, and the
  iteration runs as one fused jit when no collective crosses it);
- a run interrupted mid-stream or mid-training and resumed reproduces
  the uninterrupted model bit-for-bit (the real-SIGKILL multi-process
  variant rides ``test_distributed.py``'s slow tier via
  ``_elastic_child.py``'s ``ELASTIC_TRAIN=1`` mode);
- simulated 2-rank consensus merging computes rank-identical global
  leaves and matches the unsharded solver to f32 accumulation accuracy;
- a resume under a changed partition fails fast with code 109;
- a guard chunk-sentinel trip mid-stream replays the chunk and the
  trained model still matches the clean run bit-for-bit;
- trained models round-trip through the serve registry dtype-faithfully.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import SketchContext
from libskylark_tpu.ml import ADMMParams, BlockADMMSolver
from libskylark_tpu.ml.distributed import (
    DistributedBlockADMMTrainer,
    prepare_rank_admm,
    stream_feature_blocks,
    validate_train_partition,
)
from libskylark_tpu.ml.kernels import GaussianKernel
from libskylark_tpu.resilient import FaultPlan, SimulatedPreemption
from libskylark_tpu.streaming import ElasticParams, RowPartition
from libskylark_tpu.utils.exceptions import (
    InvalidParameters,
    WorldMismatchError,
)

pytestmark = pytest.mark.train

N, D_IN, BATCH = 32, 4, 4


def bits(x):
    return np.asarray(x).tobytes()


def make_data():
    rng = np.random.default_rng(7)
    X = rng.standard_normal((N, D_IN))
    y = np.array([1.0, 2.0] * (N // 2))
    return X, y


def make_maps(seed=11, per_map=32):
    kern = GaussianKernel(D_IN, 2.0)
    ctx = SketchContext(seed=seed)
    return [kern.create_rft(per_map, "regular", ctx) for _ in range(2)]


def make_params(**kw):
    kw.setdefault("rho", 1.0)
    kw.setdefault("lam", 0.01)
    kw.setdefault("maxiter", 8)
    kw.setdefault("data_partitions", 2)
    return ADMMParams(**kw)


def source_of(X, y, part):
    def factory(start):
        def it():
            for b in range(start, part.num_batches):
                lo = b * part.batch_rows
                hi = min(lo + part.batch_rows, part.nrows)
                yield X[lo:hi], y[lo:hi]
        return it()
    return factory


# ---------------------------------------------------------------------------
# partition validation: whole ADMM partitions per rank
# ---------------------------------------------------------------------------


class TestPartitionValidation:
    def test_aligned_partition_accepted(self):
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        assert validate_train_partition(part, 2) == N // 2

    def test_rows_not_divisible_rejected(self):
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        with pytest.raises(InvalidParameters):
            validate_train_partition(part, 5)

    def test_partition_split_across_ranks_rejected(self):
        # world=2 halves the rows at 16; data_partitions=1 means the one
        # partition (32 rows) would straddle both ranks — no owner.
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        with pytest.raises(InvalidParameters):
            validate_train_partition(part, 1)

    def test_nonpositive_partitions_rejected(self):
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        with pytest.raises(InvalidParameters):
            validate_train_partition(part, 0)


# ---------------------------------------------------------------------------
# world=1 bitwise parity vs the in-process solver
# ---------------------------------------------------------------------------


class TestWorldOneParity:
    def _distributed(self, X, y, maps, params, *, regression, **train_kw):
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        trainer = DistributedBlockADMMTrainer(
            "squared", "l2", maps, params, ElasticParams(prefetch=0)
        )
        return trainer.train(
            source_of(X, y, part), part, regression=regression, **train_kw
        )

    def test_regression_bitwise(self):
        X, y = make_data()
        maps, params = make_maps(), make_params()
        m_ref = BlockADMMSolver("squared", "l2", maps, params).train(
            X, y, regression=True
        )
        m_dist, info = self._distributed(
            X, y, maps, params, regression=True
        )
        assert bits(m_ref.W) == bits(m_dist.W)
        assert m_ref.history == m_dist.history
        assert info["iters"] == params.maxiter

    def test_classification_bitwise(self):
        X, y = make_data()
        maps, params = make_maps(), make_params()
        m_ref = BlockADMMSolver("squared", "l2", maps, params).train(X, y)
        m_dist, _ = self._distributed(
            X, y, maps, params, regression=False
        )
        assert bits(m_ref.W) == bits(m_dist.W)
        np.testing.assert_array_equal(
            np.asarray(m_ref.classes, np.float64),
            np.asarray(m_dist.classes, np.float64),
        )

    def test_info_contract(self):
        X, y = make_data()
        m, info = self._distributed(
            X, y, make_maps(), make_params(), regression=True
        )
        assert info["world_size"] == 1 and info["rank"] == 0
        assert info["rows"] == N and info["data_partitions"] == 2
        assert info["features"] == 64 and info["blocks"] == 2
        # the recorded rung IS the dtype the model trained at
        assert info["precision"] == str(np.asarray(m.W).dtype)
        assert info["escalated"] is False
        assert info["policy"]["route"] == "admm"
        assert info["recovery"]["stage"] == "distributed_block_admm"
        assert info["consensus_residual"] >= 0.0

    def test_streamed_blocks_match_prepare_bitwise(self):
        # The substrate seam under the parity above: the rowwise bucketed
        # streamed materialization, repartitioned to the columnwise
        # layout, IS _prepare's realization.
        X, y = make_data()
        maps, params = make_maps(), make_params()
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        Z_rows, Y_rows, nb = stream_feature_blocks(
            source_of(X, y, part), maps, part, ElasticParams(prefetch=0),
            targets=1,
        )
        assert nb == part.num_batches
        run = BlockADMMSolver("squared", "l2", maps, params)._prepare(
            jnp.asarray(X), y, None, True
        )
        P = params.data_partitions
        ni = N // P
        for Z, Zp_ref in zip(Z_rows, run.Zs):
            Zp = Z.reshape(P, ni, Z.shape[1]).transpose(0, 2, 1)
            assert bits(Zp) == bits(Zp_ref)


# ---------------------------------------------------------------------------
# the chunked-solver contract of ml/admm.py (pinned per its docstring)
# ---------------------------------------------------------------------------


class TestChunkedContract:
    def test_chunked_kill_resume_matches_train_bitwise(self, tmp_path):
        """``chunked()`` killed at a chunk boundary and resumed must
        reproduce not just the uninterrupted chunked run but ``train()``
        itself, bit-for-bit — the contract the distributed trainer's
        per-rank loop inherits."""
        from libskylark_tpu.resilient import ResilientParams, ResilientRunner

        X, y = make_data()
        maps, params = make_maps(), make_params()
        m_train = BlockADMMSolver("squared", "l2", maps, params).train(
            X, y, regression=True
        )

        def run(plan=None, resume=False):
            return ResilientRunner(
                BlockADMMSolver("squared", "l2", maps, params).chunked(
                    X, y, regression=True
                ),
                ResilientParams(
                    checkpoint_dir=str(tmp_path / "ck"),
                    checkpoint_every=3, resume=resume,
                ),
                fault_plan=plan,
            ).run()

        with pytest.raises(SimulatedPreemption):
            run(plan=FaultPlan(preempt_after_chunk=0))
        m_res = run(resume=True)
        assert bits(m_train.W) == bits(m_res.W)
        np.testing.assert_array_equal(m_train.history, m_res.history)


# ---------------------------------------------------------------------------
# simulated 2-rank consensus: rank-identical, matches unsharded
# ---------------------------------------------------------------------------


class TestSimulatedTwoRank:
    def test_consensus_merge_matches_unsharded(self):
        X, y = make_data()
        maps, params = make_maps(), make_params()
        m_ref = BlockADMMSolver("squared", "l2", maps, params).train(
            X, y, regression=True
        )
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=2)
        src = source_of(X, y, part)

        preps = []
        for r in (0, 1):
            ep = ElasticParams(rank=r, world_size=2, prefetch=0)
            Z_rows, Y_rows, _ = stream_feature_blocks(
                src, maps, part, ep, targets=1
            )
            preps.append(
                prepare_rank_admm(
                    "squared", "l2", maps, params, part, r, Z_rows,
                    Y_rows, regression=True,
                )
            )

        # Lockstep split schedule with the psum merged by hand — the
        # exact program structure a real 2-process world runs.
        jl = [jax.jit(p.local_step) for p in preps]
        jm = [jax.jit(p.merge_step) for p in preps]
        states = [p.state0 for p in preps]
        hist = [[], []]
        for _ in range(params.maxiter):
            outs = [
                jl[r](states[r], preps[r].Zs, preps[r].Ls, preps[r].Yp)
                for r in (0, 1)
            ]
            wi_g = np.asarray(outs[0][1]) + np.asarray(outs[1][1])
            obj_g = np.asarray(outs[0][2]) + np.asarray(outs[1][2])
            for r in (0, 1):
                states[r] = jm[r](
                    outs[r][0], jnp.asarray(wi_g), jnp.asarray(obj_g)
                )
                hist[r].append(float(states[r][-1]))

        # Global consensus leaves are recomputed IDENTICALLY per rank.
        for leaf in (0, 1, 2, 9):  # Wbar, W, mu, obj
            assert bits(states[0][leaf]) == bits(states[1][leaf])
        assert hist[0] == hist[1]
        # ...and match the unsharded solver to f32 accumulation accuracy
        # (the split/fused programs differ at the ULP level — the
        # rank_chunked_solver docstring's cross-world caveat).
        np.testing.assert_allclose(
            np.asarray(states[0][0]), np.asarray(m_ref.W),
            rtol=0, atol=1e-4,
        )
        np.testing.assert_allclose(
            hist[0], m_ref.history, rtol=1e-3, atol=1e-3
        )


# ---------------------------------------------------------------------------
# kill/resume through the trainer (in-process; real SIGKILL = slow tier)
# ---------------------------------------------------------------------------


class TestKillResume:
    def _train(self, X, y, maps, params, root, *, resume=False,
               fault_plan=None, train_fault_plan=None):
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        trainer = DistributedBlockADMMTrainer(
            "squared", "l2", maps, params,
            ElasticParams(
                checkpoint_dir=str(root), checkpoint_every=2,
                resume=resume, prefetch=0,
            ),
        )
        return trainer.train(
            source_of(X, y, part), part, regression=True,
            fault_plan=fault_plan, train_fault_plan=train_fault_plan,
        )

    def test_train_chunk_kill_resume_bitwise(self, tmp_path):
        X, y = make_data()
        maps, params = make_maps(), make_params()
        m_ref, _ = self._train(X, y, maps, params, tmp_path / "ref")
        with pytest.raises(SimulatedPreemption):
            self._train(
                X, y, maps, params, tmp_path / "ck",
                train_fault_plan=FaultPlan(preempt_after_chunk=1),
            )
        m_res, info = self._train(
            X, y, maps, params, tmp_path / "ck", resume=True
        )
        assert bits(m_ref.W) == bits(m_res.W)
        np.testing.assert_array_equal(m_ref.history, m_res.history)
        assert info["iters"] == params.maxiter

    def test_stream_kill_resume_bitwise(self, tmp_path):
        X, y = make_data()
        maps, params = make_maps(), make_params()
        m_ref, _ = self._train(X, y, maps, params, tmp_path / "ref")
        with pytest.raises(SimulatedPreemption):
            self._train(
                X, y, maps, params, tmp_path / "ck",
                fault_plan=FaultPlan(preempt_after_chunk=0),
            )
        m_res, _ = self._train(
            X, y, maps, params, tmp_path / "ck", resume=True
        )
        assert bits(m_ref.W) == bits(m_res.W)
        np.testing.assert_array_equal(m_ref.history, m_res.history)


# ---------------------------------------------------------------------------
# world/partition mismatch: the typed 109 guard
# ---------------------------------------------------------------------------


class TestWorldMismatch:
    def test_resume_under_changed_partition_raises_109(self, tmp_path):
        X, y = make_data()
        maps, params = make_maps(), make_params()

        def train(batch_rows, *, resume):
            part = RowPartition(
                nrows=N, batch_rows=batch_rows, world_size=1
            )
            trainer = DistributedBlockADMMTrainer(
                "squared", "l2", maps, params,
                ElasticParams(
                    checkpoint_dir=str(tmp_path / "ck"),
                    checkpoint_every=2, resume=resume, prefetch=0,
                ),
            )
            return trainer.train(
                source_of(X, y, part), part, regression=True
            )

        train(BATCH, resume=False)
        with pytest.raises(WorldMismatchError) as ei:
            train(2 * BATCH, resume=True)
        assert ei.value.code == 109


# ---------------------------------------------------------------------------
# guard recovery through a training chunk
# ---------------------------------------------------------------------------


class TestGuardRecovery:
    def test_bad_block_replay_preserves_bits(self, monkeypatch):
        monkeypatch.setenv("SKYLARK_GUARD", "1")
        X, y = make_data()
        maps, params = make_maps(), make_params()
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)

        def train(fault_plan=None):
            trainer = DistributedBlockADMMTrainer(
                "squared", "l2", maps, params,
                ElasticParams(prefetch=0, checkpoint_every=4),
            )
            return trainer.train(
                source_of(X, y, part), part, regression=True,
                fault_plan=fault_plan,
            )

        m_clean, _ = train()
        # Inf-scaled block at batch 2 (one-shot): the chunk sentinel
        # trips at the chunk boundary, the fold replays clean, and the
        # model comes out bit-identical.
        m_fault, info = train(FaultPlan(bad_sketch_at=2))
        assert bits(m_clean.W) == bits(m_fault.W)
        assert info["recovery"]["guarded"]
        actions = [a["action"] for a in info["recovery"]["attempts"]]
        assert "replay" in actions
        # the attempt-0 world verdict records the replay count it psummed
        world = [
            a for a in info["recovery"]["attempts"] if a["action"] == "world"
        ]
        assert world and "chunk_replays=1" in world[0]["detail"]

    def test_guard_off_skips_certification(self, monkeypatch):
        monkeypatch.setenv("SKYLARK_GUARD", "0")
        X, y = make_data()
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        trainer = DistributedBlockADMMTrainer(
            "squared", "l2", make_maps(), make_params(),
            ElasticParams(prefetch=0),
        )
        _, info = trainer.train(
            source_of(X, y, part), part, regression=True
        )
        assert info["recovery"]["guarded"] is False
        assert info["recovery"]["attempts"] == []


# ---------------------------------------------------------------------------
# serve hand-off: registry round-trip, dtype-faithful
# ---------------------------------------------------------------------------


class TestServeRoundTrip:
    def test_register_save_load_roundtrip(self, tmp_path):
        from libskylark_tpu.serve.registry import Registry

        X, y = make_data()
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        reg = Registry()
        trainer = DistributedBlockADMMTrainer(
            "squared", "l2", make_maps(), make_params(),
            ElasticParams(prefetch=0),
        )
        model, info = trainer.train(
            source_of(X, y, part), part, regression=True,
            registry=reg, register_as="admm-reg",
        )
        assert info["registered"] == "admm-reg"
        assert reg.get_model("admm-reg") is model
        pred = np.asarray(model.predict(jnp.asarray(X)))

        # dtype-faithful save/load → a second registry serves identical
        # bits from disk.
        path = str(tmp_path / "model.json")
        model.save(path)
        reg2 = Registry()
        loaded = reg2.load_model("admm-disk", path)
        assert np.asarray(loaded.W).dtype == np.asarray(model.W).dtype
        assert bits(loaded.W) == bits(model.W)
        np.testing.assert_array_equal(
            np.asarray(loaded.predict(jnp.asarray(X))), pred
        )


# ---------------------------------------------------------------------------
# telemetry: the train.* counter group folds into snapshot()
# ---------------------------------------------------------------------------


class TestTrainTelemetry:
    def test_train_counters_fold_into_snapshot(self, monkeypatch, tmp_path):
        monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
        monkeypatch.setenv(
            "SKYLARK_TELEMETRY_DIR", str(tmp_path / "ledger")
        )
        from libskylark_tpu import telemetry

        X, y = make_data()
        part = RowPartition(nrows=N, batch_rows=BATCH, world_size=1)
        trainer = DistributedBlockADMMTrainer(
            "squared", "l2", make_maps(), make_params(),
            ElasticParams(prefetch=0),
        )
        trainer.train(source_of(X, y, part), part, regression=True)
        snap = telemetry.snapshot()
        assert "train" in snap
        assert snap["train"]["runs"] >= 1
        assert snap["train"]["iterations"] >= 8
        assert snap["train"]["consensus"] >= 8
