"""Pallas two-pass segment-sum: interpret-mode parity + gating.

The compiled kernel is hardware-gated (``tests/_hw_guards.py`` +
``experiments/scatter_probe.py``); here the algorithm itself is verified
against ``jax.ops.segment_sum`` in interpret mode on CPU, including the
partition-boundary and max-collision edge cases the two-pass structure
could get wrong.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import sparse as jsparse

from libskylark_tpu import SketchContext
from libskylark_tpu.sketch import CWT, SJLT
from libskylark_tpu.sketch.pallas_scatter import (
    _plan,
    segment_sum_flat,
    supported,
)


def _ref(vals, keys, T):
    out = np.zeros(T, np.float64)
    np.add.at(out, keys, vals.astype(np.float64))
    return out.astype(np.float32)


class TestKernelParity:
    @pytest.mark.parametrize(
        "nnz,T",
        [
            (10_000, 5_000),
            (20_000, 200_000),
            (8_193, 1024),  # one entry past the pad boundary
            (9_000, 1 << 17),
        ],
    )
    @pytest.mark.slow
    def test_random_keys(self, rng, nnz, T):
        keys = rng.integers(0, T, nnz).astype(np.int32)
        vals = rng.standard_normal(nnz).astype(np.float32)
        out = np.asarray(
            segment_sum_flat(
                jnp.asarray(vals), jnp.asarray(keys), T, interpret=True
            )
        )
        np.testing.assert_allclose(out, _ref(vals, keys, T), rtol=1e-5,
                                   atol=1e-5)

    @pytest.mark.slow
    def test_partition_boundaries_and_collisions(self, rng):
        nnz, T = 16_384, 300_000
        K, P, V = _plan(nnz, T)
        # Adversarial keys: partition edges (0, V-1, V, 2V-1, T-1) and a
        # hot segment taking ~half the entries (worst-case collisions).
        edges = np.array([0, V - 1, V, 2 * V - 1, T - 1], np.int32)
        keys = np.concatenate([
            np.repeat(edges, 100),
            np.full(nnz // 2, V + 7, np.int32),  # hot segment
            rng.integers(0, T, nnz - 500 - nnz // 2).astype(np.int32),
        ])
        vals = rng.standard_normal(nnz).astype(np.float32)
        out = np.asarray(
            segment_sum_flat(
                jnp.asarray(vals), jnp.asarray(keys), T, interpret=True
            )
        )
        np.testing.assert_allclose(out, _ref(vals, keys, T), rtol=1e-4,
                                   atol=1e-4)

    def test_gate(self):
        assert not supported(100, 5000)  # too small to amortize
        assert not supported(100_000, 500)  # degenerate segment count
        assert supported(100_000, 1 << 20)
        os.environ["SKYLARK_NO_PALLAS"] = "1"
        try:
            assert not supported(100_000, 1 << 20)
        finally:
            del os.environ["SKYLARK_NO_PALLAS"]


class TestHashIntegration:
    @pytest.mark.slow
    def test_dense_output_matches_xla_path(self, rng):
        """CWT/SJLT dense_output through the kernel (interpret) must be
        bit-compatible with the XLA segment_sum path."""
        n, m, s, nnz = 30_000, 64, 64, 9_000
        rows = rng.integers(0, n, nnz).astype(np.int32)
        cols = rng.integers(0, m, nnz).astype(np.int32)
        data = rng.standard_normal(nnz).astype(np.float32)
        A = jsparse.BCOO(
            (jnp.asarray(data), jnp.asarray(np.stack([rows, cols], 1))),
            shape=(n, m),
        )
        for cls, kw in [(CWT, {}), (SJLT, {"nnz": 2})]:
            S = cls(n, s, SketchContext(seed=5), **kw)
            os.environ["SKYLARK_PALLAS_SCATTER"] = "interpret"
            try:
                out_p = np.asarray(S.apply(A, "columnwise", dense_output=True))
            finally:
                os.environ["SKYLARK_PALLAS_SCATTER"] = "0"
            out_x = np.asarray(S.apply(A, "columnwise", dense_output=True))
            del os.environ["SKYLARK_PALLAS_SCATTER"]
            np.testing.assert_allclose(out_p, out_x, rtol=1e-5, atol=1e-5)
