"""Regression tests for review findings: sparse SVD path, rank validation,
1-D hash-sketch apply."""

import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import sparse as jsparse

from libskylark_tpu import SketchContext
from libskylark_tpu.linalg import SVDParams, approximate_svd
from libskylark_tpu.sketch import CWT


@pytest.mark.slow
def test_approximate_svd_on_bcoo(rng):
    dense = rng.standard_normal((60, 20))
    dense[rng.random((60, 20)) < 0.6] = 0.0
    A = jsparse.BCOO.fromdense(jnp.asarray(dense))
    U, s, V = approximate_svd(A, 5, SketchContext(seed=11), SVDParams(num_iterations=1))
    s_true = np.linalg.svd(dense, compute_uv=False)
    np.testing.assert_allclose(np.asarray(s)[:2], s_true[:2], rtol=0.1)


def test_rank_too_large_raises(rng):
    A = jnp.asarray(rng.standard_normal((30, 10)))
    with pytest.raises(ValueError, match="rank"):
        approximate_svd(A, 50, SketchContext(seed=1))


def test_hash_sketch_1d_vector(rng):
    n, s = 40, 12
    v = jnp.asarray(rng.standard_normal(n))
    S = CWT(n, s, SketchContext(seed=3))
    out_vec = S.apply(v, "columnwise")
    out_mat = S.apply(v[:, None], "columnwise")
    assert out_vec.shape == (s,)
    np.testing.assert_allclose(np.asarray(out_vec), np.asarray(out_mat[:, 0]))
    out_r = S.apply(v, "rowwise")
    out_r_mat = S.apply(v[None, :], "rowwise")
    assert out_r.shape == (s,)
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_r_mat[0]))


def test_cli_sparse_path(tmp_path, rng):
    from libskylark_tpu.cli.svd import main
    from libskylark_tpu.io import write_libsvm

    X = rng.standard_normal((30, 10))
    X[rng.random((30, 10)) < 0.5] = 0.0
    write_libsvm(tmp_path / "d", X, np.ones(30))
    rc = main(
        [str(tmp_path / "d"), "--sparse", "--rank", "3", "--prefix", str(tmp_path / "o")]
    )
    assert rc == 0
    assert np.load(tmp_path / "o.S.npy").shape == (3,)


def test_kernel_probe_runs_inside_jit_trace(monkeypatch):
    """The one-time Pallas-scatter probe must execute eagerly even when
    its first caller is mid-trace: under omnistaging the probe's ops
    would otherwise be staged into the caller's trace and the float()
    readback would raise ConcretizationTypeError — which the blanket
    except would latch as a permanent (and wrong) kernel-broken verdict."""
    import jax

    from libskylark_tpu.sketch import hash as hash_mod
    from libskylark_tpu.sketch import pallas_scatter

    # Stand-in validator: same jnp-op + float() shape as the real
    # self_check, minus the Pallas call (not lowerable on CPU compiled
    # mode); what is under test is the trace-escape, not the kernel.
    def fake_self_check():
        x = jnp.arange(8.0)
        return float(jnp.max(x) - jnp.max(x))

    monkeypatch.setattr(pallas_scatter, "self_check", fake_self_check)
    monkeypatch.setattr(hash_mod, "_KERNEL_COMPILES", None)

    result = {}

    @jax.jit
    def traced(v):
        result["ok"] = hash_mod._kernel_compiles()
        return v * 2

    traced(jnp.ones(4))
    assert result["ok"] is True
    assert hash_mod._KERNEL_COMPILES is True


def test_halton_window_tiered_digits_bit_identical():
    """window()'s per-base digit tiers must be BIT-identical to the full
    41-digit loop (skipped iterations add exactly 0.0)."""
    from libskylark_tpu.core.quasirand import (
        LeapedHaltonSequence,
        primes,
        radical_inverse,
    )

    seq = LeapedHaltonSequence(200)
    for idx0, num in ((0, 16), (1000, 8), (123456, 4)):
        out = seq.window(idx0, num, dtype=jnp.float64)
        itype = jnp.int64
        idx = (idx0 + jnp.arange(num, dtype=itype))[:, None] * seq.leap
        p = jnp.asarray(primes(seq.d))[None, :].astype(itype)
        full = radical_inverse(p, idx, ndigits=41).astype(jnp.float64)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(full))


def test_halton_window_exact_at_power_boundaries():
    """Digit counts must be exact integers: float logs undercount at
    p^k boundaries (review r5), dropping the leading digit for those
    columns.  Constructs a window whose max index sits exactly at a
    prime power and checks against the full 41-digit loop."""
    from libskylark_tpu.core.quasirand import (
        LeapedHaltonSequence,
        primes,
        radical_inverse,
    )

    seq = LeapedHaltonSequence(30, leap=1)  # leap=1: indices are raw
    p5 = int(primes(30)[2])  # base 5
    idx0 = p5**6 - 3  # window straddles 5^6 exactly
    out = seq.window(idx0, 6, dtype=jnp.float64)
    idx = (idx0 + jnp.arange(6, dtype=jnp.int64))[:, None]
    p = jnp.asarray(primes(seq.d))[None, :].astype(jnp.int64)
    full = radical_inverse(p, idx, ndigits=41).astype(jnp.float64)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(full))


def test_halton_window_zero_dims():
    from libskylark_tpu.core.quasirand import LeapedHaltonSequence

    out = LeapedHaltonSequence(0, leap=7).window(0, 4)
    assert out.shape == (4, 0)


@pytest.mark.guard
def test_solver_entrypoints_document_and_populate_recovery():
    """Static contract check (ISSUE PR 4): every public solver entrypoint
    that returns ``(x, info)`` must document ``info["recovery"]`` in its
    docstring AND populate it in source, so the guard ledger can never be
    silently dropped from one solver's info dict."""
    import inspect

    from libskylark_tpu.linalg.least_squares import (
        approximate_least_squares,
        streaming_least_squares,
    )
    from libskylark_tpu.ml.krr import (
        approximate_kernel_ridge,
        streaming_approximate_kernel_ridge,
    )
    from libskylark_tpu.solvers.accelerated import (
        faster_least_squares,
        lsrn_least_squares,
    )
    from libskylark_tpu.streaming.drivers import sketch_least_squares

    entrypoints = [
        approximate_least_squares,
        streaming_least_squares,
        faster_least_squares,
        lsrn_least_squares,
        sketch_least_squares,
        approximate_kernel_ridge,  # ledger rides on model.info
        streaming_approximate_kernel_ridge,
    ]
    for fn in entrypoints:
        doc = inspect.getdoc(fn) or ""
        assert '"recovery"' in doc or "recovery" in doc, (
            f"{fn.__module__}.{fn.__name__} returns an info dict but its "
            f'docstring does not document info["recovery"]'
        )
        src = inspect.getsource(fn)
        assert '"recovery"' in src or "report.to_dict()" in src or (
            # thin wrappers may delegate the ledger to the layer below —
            # but then the delegate must populate it
            "sketch_least_squares" in src
        ), (
            f"{fn.__module__}.{fn.__name__} does not populate "
            f'info["recovery"] (or delegate to a layer that does)'
        )


@pytest.mark.telemetry
def test_solver_entrypoints_emit_run_summary():
    """Static contract check (ISSUE PR 5): every public solver entrypoint
    that returns ``(x, info)`` must emit a terminal
    ``telemetry.run_summary`` event carrying its ``info`` dict — or
    delegate to the layer that does — so an enabled ledger always closes
    with the counters-vs-info record the acceptance check reads."""
    import inspect

    from libskylark_tpu.linalg.least_squares import (
        approximate_least_squares,
        streaming_least_squares,
    )
    from libskylark_tpu.ml.krr import (
        approximate_kernel_ridge,
        streaming_approximate_kernel_ridge,
    )
    from libskylark_tpu.solvers.accelerated import (
        faster_least_squares,
        lsrn_least_squares,
    )
    from libskylark_tpu.streaming.drivers import sketch_least_squares

    entrypoints = [
        approximate_least_squares,
        streaming_least_squares,
        faster_least_squares,
        lsrn_least_squares,
        sketch_least_squares,
        approximate_kernel_ridge,
        streaming_approximate_kernel_ridge,
    ]
    for fn in entrypoints:
        src = inspect.getsource(fn)
        assert "telemetry.run_summary(" in src or (
            # thin wrappers may delegate the terminal event to the
            # streaming driver below — which emits it itself
            "sketch_least_squares" in src or "kernel_ridge(" in src
        ), (
            f"{fn.__module__}.{fn.__name__} returns (x, info) but never "
            "emits a terminal telemetry.run_summary (or delegates to a "
            "layer that does)"
        )


@pytest.mark.telemetry
def test_disabled_telemetry_registers_no_atexit_hooks():
    """With ``SKYLARK_TELEMETRY`` unset/0, importing the library and
    emitting disabled-path events must leave the process's atexit table
    untouched (the ledger registers its flush hook only when a file
    actually opens).  Measured AFTER the library import in a fresh
    subprocess: jax itself registers atexit hooks at import time, so the
    contract is 'telemetry adds zero', not 'the table is empty'."""
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ['SKYLARK_TELEMETRY'] = '0'\n"
        "os.environ.pop('SKYLARK_TELEMETRY_DIR', None)\n"
        "import atexit\n"
        "import libskylark_tpu\n"
        "from libskylark_tpu import telemetry\n"
        "base = atexit._ncallbacks()\n"
        "telemetry.emit('probe', 'noop', k=1)\n"
        "telemetry.inc('noop.counter')\n"
        "with telemetry.span('noop.span'):\n"
        "    pass\n"
        "assert telemetry.ledger_path() is None, telemetry.ledger_path()\n"
        "assert atexit._ncallbacks() == base, (base, atexit._ncallbacks())\n"
        "print('ZERO-ATEXIT-OK')\n"
    )
    import os

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        env=env,
        timeout=110,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "ZERO-ATEXIT-OK" in out.stdout


@pytest.mark.policy
def test_snapshot_folds_policy_counter_group():
    """Static contract check (ISSUE PR 9): ``telemetry.snapshot()`` must
    fold the ``policy.*`` counters into a ``"policy"`` group, and the
    terminal ``run_summary`` must flush the policy store BEFORE its own
    enabled gate — profiles persist even with telemetry off."""
    import importlib
    import inspect

    # the telemetry package exports a report() *function*; reach the
    # module itself through importlib
    report = importlib.import_module("libskylark_tpu.telemetry.report")

    snap_src = inspect.getsource(report.snapshot)
    assert '"policy"' in snap_src and "policy." in snap_src, (
        "telemetry.snapshot() no longer folds the policy.* counter "
        'group into snap["policy"] (docs/autotuning.md contract)'
    )
    rs_src = inspect.getsource(report.run_summary)
    flush_at = rs_src.find("policy.flush")
    gate_at = rs_src.find("config.enabled()")
    assert flush_at != -1, (
        "telemetry.run_summary() no longer flushes the policy profile "
        "store (warm-start profiles would silently stop persisting)"
    )
    assert gate_at == -1 or flush_at < gate_at, (
        "policy.flush must run before run_summary's telemetry-enabled "
        "gate: profiles persist even with SKYLARK_TELEMETRY off"
    )


@pytest.mark.serve
def test_snapshot_folds_serve_counter_group():
    """Static contract check (ISSUE PR 10): ``telemetry.snapshot()`` must
    fold the ``serve.*`` counters into a ``"serve"`` group (with the
    derived coalesce ratio and latency percentiles) — the SLO surface
    docs/serving.md points operators at."""
    import importlib
    import inspect

    report = importlib.import_module("libskylark_tpu.telemetry.report")
    snap_src = inspect.getsource(report.snapshot)
    assert '"serve"' in snap_src and "serve." in snap_src, (
        "telemetry.snapshot() no longer folds the serve.* counter "
        'group into snap["serve"] (docs/serving.md contract)'
    )
    assert "coalesce_ratio" in snap_src, (
        "snapshot()['serve'] no longer derives the coalesce ratio"
    )


@pytest.mark.serve
def test_disabled_telemetry_server_is_pure_and_hookless():
    """With ``SKYLARK_TELEMETRY`` unset/0, running a full serve
    round-trip (admit -> coalesce -> execute -> respond) must add zero
    atexit hooks AND return bit-identical results to a second same-seed
    server in the same process — the telemetry fast path cannot perturb
    the serve numerics or leave process-lifetime residue."""
    import os
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ['SKYLARK_TELEMETRY'] = '0'\n"
        "os.environ.pop('SKYLARK_TELEMETRY_DIR', None)\n"
        "import atexit\n"
        "import numpy as np\n"
        "import libskylark_tpu\n"
        "from libskylark_tpu import serve\n"
        "from libskylark_tpu.core.context import SketchContext\n"
        "rng = np.random.default_rng(0)\n"
        "A = rng.standard_normal((48, 4))\n"
        "bs = [rng.standard_normal(48) for _ in range(3)]\n"
        "def run():\n"
        "    p = serve.ServeParams(warm_start=False, prime=False)\n"
        "    srv = serve.Server(p, seed=5)\n"
        "    srv.registry.register_system('s', A,\n"
        "                                 context=SketchContext(seed=2))\n"
        "    futs = [srv.submit(serve.make_request('ls_solve', system='s',\n"
        "                                          b=b)) for b in bs]\n"
        "    srv.start()\n"
        "    out = [np.asarray(f.result()['result']) for f in futs]\n"
        "    srv.stop()\n"
        "    return out\n"
        "one = run()\n"
        "base = atexit._ncallbacks()\n"
        "two = run()\n"
        "assert atexit._ncallbacks() == base, (base, atexit._ncallbacks())\n"
        "assert all((a == b).all() for a, b in zip(one, two))\n"
        "from libskylark_tpu import telemetry\n"
        "assert telemetry.ledger_path() is None\n"
        "print('SERVE-PURE-OK')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        env=env,
        timeout=110,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "SERVE-PURE-OK" in out.stdout


@pytest.mark.overlap
def test_no_read_after_donation_lint():
    """Static donation lint (ISSUE PR 11): buffer donation invalidates
    the argument after the call, so every ``donate_argnums`` site in the
    library must live in an audited module, the engine must snapshot via
    ``plans.copy_for_donation`` before handing an accumulator to a
    donating executable, and its chunk-boundary sync must run BEFORE the
    sentinel read / checkpoint capture — a checkpoint must never hold a
    buffer a donating step is still allowed to alias.  A grep over call
    sites rather than a runtime probe: CPU silently ignores donation, so
    only TPU runs would catch a read-after-donate dynamically."""
    import inspect
    import pathlib

    import libskylark_tpu

    pkg = pathlib.Path(libskylark_tpu.__file__).parent
    # Every module allowed to spell donate_argnums; new sites must be
    # audited for read-after-donation and added here deliberately.
    audited = {
        pkg / "plans" / "plan.py",
        pkg / "streaming" / "drivers.py",
        # stream_feature_blocks' row-slot buffer write: each donated
        # buffer enters `write` exactly once per step and the old acc is
        # discarded; the engine's _entry_acc snapshot (gated on the same
        # donation_enabled()) covers sentinel replay, and checkpoints
        # capture post-chunk outputs, never donated inputs.
        pkg / "ml" / "distributed.py",
    }
    offenders = [
        str(p.relative_to(pkg))
        for p in sorted(pkg.rglob("*.py"))
        if p not in audited and "donate_argnums" in p.read_text()
    ]
    assert not offenders, (
        f"unaudited donate_argnums sites: {offenders}; audit each for "
        "read-after-donation (donated buffers are invalid after the "
        "call) and extend the whitelist in this test"
    )

    from libskylark_tpu.streaming import engine

    src = inspect.getsource(engine.run_stream)
    assert "copy_for_donation" in src, (
        "run_stream no longer snapshots the accumulator via "
        "plans.copy_for_donation before donating folds — a resumed "
        "checkpoint could alias a donated buffer"
    )
    sync_at = src.find("chunk_sync")
    sentinel_at = src.find("stream.sentinel_checks")
    assert sync_at != -1, (
        "run_stream lost its chunk-boundary sync (overlap contract: "
        "one block_until_ready per chunk, before state capture)"
    )
    assert sentinel_at == -1 or sync_at < sentinel_at, (
        "chunk_sync must run before the guard-sentinel read / "
        "checkpoint capture: an in-flight donated accumulator must "
        "never be observed by host-side state"
    )

    # kernel_ridge's donating update is the other audited site: its
    # donated arguments must be rebound from the call's RESULT, never
    # read again from the pre-call names.
    from libskylark_tpu.streaming import drivers

    kr = inspect.getsource(drivers.kernel_ridge)
    assert "donate_argnums" not in kr or "copy_for_donation" in kr or (
        "= update(" in kr
    ), "kernel_ridge must rebind donated accumulators from update()'s result"


def test_error_codes_documented_and_traceable(tmp_path, monkeypatch):
    """Error-code contract (ISSUE PR 12): the 100-115 ladder is only
    useful if every code (a) has a row in docs/fault_tolerance.md's
    matrix a supervisor can act on, and (b) surfaces through
    ``telemetry.error_event`` with a mandatory ``code`` attr so traces,
    the ledger, and the ``error.code.<n>`` counters all agree.  Static
    over the exception taxonomy so ADDING a code without documenting it
    fails here, not in an incident."""
    import inspect
    import pathlib

    from libskylark_tpu import telemetry
    from libskylark_tpu.utils import exceptions as ex

    classes = [
        obj
        for _, obj in inspect.getmembers(ex, inspect.isclass)
        if issubclass(obj, ex.SkylarkError)
    ]
    codes = {cls.code for cls in classes}
    assert codes == set(range(100, 119)), codes  # the ladder, no gaps

    doc = (
        pathlib.Path(__file__).parent.parent / "docs" / "fault_tolerance.md"
    ).read_text()
    undocumented = [c for c in sorted(codes) if f"| {c} |" not in doc]
    assert not undocumented, (
        f"error codes missing a docs/fault_tolerance.md matrix row: "
        f"{undocumented}"
    )

    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    telemetry.configure(tmp_path)
    telemetry.reset()
    try:
        for cls in classes:
            tctx = telemetry.mint(f"probe-{cls.code}")
            with telemetry.activate([tctx]):
                telemetry.error_event("probe", cls("probe"))
            evs = [e for e in tctx.events if e["kind"] == "error"]
            assert evs and evs[-1]["code"] == cls.code, cls
        counters = telemetry.REGISTRY.snapshot()["counters"]
        for cls in classes:
            assert counters.get(f"error.code.{cls.code}", 0) >= 1, cls
        telemetry.flush()
        import json

        ledger = [
            json.loads(line)
            for line in open(telemetry.ledger_path(), encoding="utf-8")
        ]
        ledger_codes = {
            r["attrs"]["code"] for r in ledger if r["kind"] == "error"
        }
        assert codes <= ledger_codes
    finally:
        telemetry.close()
        telemetry.configure(None)
        telemetry.reset()


def test_env_knobs_documented():
    """Env-knob doc contract (ISSUE PR 14): every ``SKYLARK_*``
    environment variable the library reads must appear somewhere under
    ``docs/`` — a knob an operator cannot discover is a support
    incident, not a feature.  Static census: grep the package for
    environ/getenv reads (with a short window for wrapped call sites)
    and assert each harvested token has a docs mention."""
    import pathlib
    import re

    root = pathlib.Path(__file__).parent.parent
    knobs = set()
    for path in (root / "libskylark_tpu").rglob("*.py"):
        lines = path.read_text(encoding="utf-8").splitlines()
        for i, line in enumerate(lines):
            if "environ" in line or "getenv" in line:
                window = "\n".join(lines[i : i + 3])
                knobs.update(re.findall(r"SKYLARK_[A-Z0-9_]+", window))
    # The census going empty means the grep rotted, not that the
    # library grew knob-free — fail loudly either way.
    assert len(knobs) >= 20, f"env-knob census looks stale: {sorted(knobs)}"
    docs = "\n".join(
        p.read_text(encoding="utf-8")
        for p in sorted((root / "docs").glob("*.md"))
    )
    undocumented = sorted(k for k in knobs if k not in docs)
    assert not undocumented, (
        f"SKYLARK_* knobs read by the library but absent from docs/: "
        f"{undocumented}"
    )


@pytest.mark.graph
@pytest.mark.serve
def test_graph_serve_ops_error_envelopes():
    """Served graph-op contract (ISSUE PR 15): ``ppr``/``ase_embed``
    are first-class protocol ops (graph-scoped placement keys), a bad
    graph name or malformed query resolves to a structured 102 envelope
    AT THE DOOR (never raised across the serving boundary), and the new
    ops shed through the same 112/113 admission/deadline ladder as
    every other op."""
    import time

    from libskylark_tpu import serve
    from libskylark_tpu.graph import SimpleGraph
    from libskylark_tpu.serve import protocol
    from libskylark_tpu.utils import exceptions as ex

    assert "ppr" in protocol.OPS and "ase_embed" in protocol.OPS
    assert protocol.placement_key({"op": "ppr", "graph": "g"}) == "ppr:g"
    assert protocol.placement_key({"op": "ase_embed", "graph": "g"}) == "ase:g"

    G = SimpleGraph([(i, j) for i in range(4) for j in range(4, 9)])
    srv = serve.Server(
        serve.ServeParams(max_queue=2, warm_start=False, prime=False)
    )
    srv.register_graph("g", G, k=2)

    # 102 at the door: validation failures resolve without a worker.
    for req in (
        dict(op="ppr", graph="nope", seeds=[0]),
        dict(op="ppr", graph="g", seeds=[]),
        dict(op="ppr", graph="g", seeds=["ghost"]),
        dict(op="ppr", graph="g", seeds=[999]),
        dict(op="ase_embed", graph="nope", ids=[0]),
        dict(op="ase_embed", graph="g"),
        dict(op="ase_embed", graph="g", ids=[0], neighbors=[1]),
        dict(op="ase_embed", graph="g", neighbors=[]),
    ):
        resp = srv.submit(req).result()
        assert not resp["ok"], req
        assert resp["error"]["code"] == 102, (req, resp["error"])
        with pytest.raises(ex.InvalidParameters):
            serve.raise_for_error(resp)

    # 112: queue full (worker not started) sheds the third request;
    # the first admitted one carries a deadline for the 113 check below.
    fd = srv.submit(dict(op="ppr", graph="g", seeds=[2], deadline_ms=1))
    f1 = srv.submit(dict(op="ase_embed", graph="g", ids=[1]))
    shed = srv.call(op="ppr", graph="g", seeds=[1])
    assert not shed["ok"] and shed["error"]["code"] == 112
    with pytest.raises(ex.AdmissionError):
        serve.raise_for_error(shed)

    # 113: the lapsed deadline sheds at dispatch once the worker drains.
    time.sleep(0.05)
    srv.start()
    assert f1.result()["ok"]
    late = fd.result()
    srv.stop()
    assert not late["ok"] and late["error"]["code"] == 113
    with pytest.raises(ex.DeadlineExceededError):
        serve.raise_for_error(late)


@pytest.mark.graph
def test_graph_marker_registered_tier1():
    """Marker contract (ISSUE PR 15): the ``graph`` marker must stay a
    registered tier-1 mark with a hard per-test alarm — graph tests
    drive elastic folds and a live serve worker, either of which could
    otherwise wedge the tier-1 run.  Static over conftest so dropping
    the mark (or demoting it to slow) fails here."""
    import pathlib

    src = (pathlib.Path(__file__).parent / "conftest.py").read_text()
    assert '"graph": GRAPH_TIMEOUT_S' in src, (
        "the graph marker lost its _TIMEOUT_MARKS alarm entry"
    )
    assert "GRAPH_TIMEOUT_S = 120" in src
    assert '"markers",\n        "graph:' in src, (
        "the graph marker is no longer registered via addinivalue_line"
    )


@pytest.mark.train
def test_train_marker_registered_tier1():
    """Marker contract (ISSUE PR 17): the ``train`` marker must stay a
    registered tier-1 mark with a hard per-test alarm — distributed-
    training tests stream elastic folds and run multi-chunk kill/resume
    rounds, either of which could otherwise wedge the tier-1 run."""
    import pathlib

    src = (pathlib.Path(__file__).parent / "conftest.py").read_text()
    assert '"train": TRAIN_TIMEOUT_S' in src, (
        "the train marker lost its _TIMEOUT_MARKS alarm entry"
    )
    assert "TRAIN_TIMEOUT_S = 180" in src
    assert '"markers",\n        "train:' in src, (
        "the train marker is no longer registered via addinivalue_line"
    )


@pytest.mark.train
def test_snapshot_folds_train_counter_group():
    """Static contract check (ISSUE PR 17): ``telemetry.snapshot()``
    must fold the ``train.*`` counters into a ``"train"`` group — the
    distributed trainer's runs/iterations/consensus/escalations surface
    docs/distributed_training.md points operators at.  Conditional like
    the router/autoscale groups: absent until a trainer ran."""
    import importlib
    import inspect

    report = importlib.import_module("libskylark_tpu.telemetry.report")
    snap_src = inspect.getsource(report.snapshot)
    assert '"train"' in snap_src and "train." in snap_src, (
        "telemetry.snapshot() no longer folds the train.* counter "
        'group into snap["train"] (docs/distributed_training.md contract)'
    )
