"""Preemption-safe runtime tests: durable checkpoints, chunked execution,
fault injection (``faults`` marker — tier-1, per-test timeout via conftest).

The load-bearing guarantees:

- a run killed at a chunk boundary and resumed from its checkpoint is
  BIT-FOR-BIT identical to the uninterrupted chunked run (counter-based
  RNG + deterministic rebuild of everything outside the state pytree);
- a corrupt newest checkpoint falls back to the previous rotation slot;
- transient IO errors during a save are retried with backoff;
- NaN/Inf divergence halts with the best iterate attached, never silently
  returns garbage.
"""

import json
import os
import zlib

import jax.numpy as jnp
import numpy as np
import pytest

from libskylark_tpu import SketchContext
from libskylark_tpu.linalg import SVDParams, approximate_svd, approximate_svd_chunked
from libskylark_tpu.ml import ADMMParams, BlockADMMSolver
from libskylark_tpu.ml.kernels import GaussianKernel
from libskylark_tpu.resilient import (
    ChunkedSolver,
    FaultPlan,
    ResilientParams,
    ResilientRunner,
    SimulatedPreemption,
    corrupt_checkpoint,
    with_retries,
)
from libskylark_tpu.solvers import KrylovParams, cg, cg_chunked, lsqr, lsqr_chunked
from libskylark_tpu.utils import (
    CheckpointError,
    CheckpointStore,
    ConvergenceError,
    IOError_,
    load_solver_state,
    save_solver_state,
)


def bits(x):
    return np.asarray(x).tobytes()


# ---------------------------------------------------------------------------
# Checkpoint format: awkward pytrees, validation, CRC


class TestCheckpointFormat:
    def test_roundtrip_awkward_pytree(self, tmp_path):
        state = {
            "bf16": jnp.asarray([1.5, -2.25, 0.125], jnp.bfloat16),
            "scalar0d": jnp.asarray(3.5),
            "count": jnp.asarray(7, jnp.int32),
            "nested": (
                {"a": jnp.ones((2, 3)), "b": [jnp.zeros((1,), jnp.float32)]},
                jnp.asarray([True, False]),
            ),
        }
        save_solver_state(tmp_path / "ck", state, {"iter": 7})
        restored, meta = load_solver_state(tmp_path / "ck", like=state)
        assert meta["iter"] == 7
        assert np.asarray(restored["bf16"]).dtype == np.asarray(state["bf16"]).dtype
        np.testing.assert_array_equal(
            np.asarray(restored["bf16"], np.float32),
            np.asarray(state["bf16"], np.float32),
        )
        assert np.asarray(restored["scalar0d"]).shape == ()
        assert restored["count"].dtype == np.int32
        np.testing.assert_array_equal(restored["nested"][0]["a"], np.ones((2, 3)))
        np.testing.assert_array_equal(restored["nested"][1], [True, False])

    def test_flat_load_without_like(self, tmp_path):
        state = [jnp.arange(4.0), jnp.asarray(2)]
        save_solver_state(tmp_path / "ck", state)
        leaves, meta = load_solver_state(tmp_path / "ck")
        assert len(leaves) == 2
        np.testing.assert_array_equal(leaves[0], np.arange(4.0))

    def test_wrong_object_type_rejected(self, tmp_path):
        meta = {"skylark_object_type": "model", "num_leaves": 0, "metadata": {}}
        np.savez(
            tmp_path / "ck.npz",
            __meta__=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
        )
        with pytest.raises(IOError_, match="skylark_object_type"):
            load_solver_state(tmp_path / "ck")

    def test_num_leaves_mismatch_rejected(self, tmp_path):
        save_solver_state(tmp_path / "ck", [jnp.ones(2), jnp.ones(3)])
        with np.load(tmp_path / "ck.npz") as data:
            kept = {k: data[k] for k in data.files if k != "leaf_1"}
        np.savez(tmp_path / "ck.npz", **kept)
        with pytest.raises(CheckpointError, match="num_leaves"):
            load_solver_state(tmp_path / "ck")

    def test_crc_mismatch_rejected(self, tmp_path):
        save_solver_state(tmp_path / "ck", [jnp.arange(8.0)])
        with np.load(tmp_path / "ck.npz") as data:
            arrs = {k: data[k] for k in data.files}
        arrs["leaf_0"] = arrs["leaf_0"] + 1.0  # silent data damage
        np.savez(tmp_path / "ck.npz", **arrs)
        with pytest.raises(CheckpointError, match="CRC32"):
            load_solver_state(tmp_path / "ck")

    def test_like_leaf_count_mismatch_rejected(self, tmp_path):
        save_solver_state(tmp_path / "ck", [jnp.ones(2)])
        with pytest.raises(CheckpointError, match="prototype"):
            load_solver_state(tmp_path / "ck", like=[jnp.ones(2), jnp.ones(2)])

    def test_handle_released_after_load(self, tmp_path):
        # The np.load handle must not outlive the call (fd leak).
        save_solver_state(tmp_path / "ck", [jnp.ones(2)])
        for _ in range(64):  # would exhaust a leaked-per-call fd budget fast
            load_solver_state(tmp_path / "ck")
        os.remove(tmp_path / "ck.npz")


class TestCheckpointStore:
    def test_rotation_keeps_last_n(self, tmp_path):
        store = CheckpointStore(tmp_path, keep_last=3)
        for step in [2, 4, 6, 8, 10]:
            store.save({"x": jnp.full((2,), float(step))}, step=step)
        assert store.steps() == [6, 8, 10]
        state, meta, step = store.load_latest(like={"x": jnp.zeros(2)})
        assert step == 10 and meta["step"] == 10
        np.testing.assert_array_equal(state["x"], [10.0, 10.0])

    def test_empty_dir_returns_none(self, tmp_path):
        assert CheckpointStore(tmp_path).load_latest() is None

    @pytest.mark.faults
    def test_corrupt_newest_falls_back_to_previous_slot(self, tmp_path):
        store = CheckpointStore(tmp_path, keep_last=3)
        path6 = store.save({"x": jnp.full((2,), 6.0)}, step=6)
        path8 = store.save({"x": jnp.full((2,), 8.0)}, step=8)
        corrupt_checkpoint(path8)
        state, meta, step = store.load_latest(like={"x": jnp.zeros(2)})
        assert step == 6
        np.testing.assert_array_equal(state["x"], [6.0, 6.0])

    @pytest.mark.faults
    def test_all_slots_corrupt_raises(self, tmp_path):
        store = CheckpointStore(tmp_path, keep_last=2)
        for step in [2, 4]:
            corrupt_checkpoint(store.save({"x": jnp.ones(2)}, step=step))
        with pytest.raises(CheckpointError, match="no valid checkpoint"):
            store.load_latest(like={"x": jnp.zeros(2)})

    def test_save_fsyncs_file_and_directory_before_rotation(
        self, tmp_path, monkeypatch
    ):
        """Satellite: the new slot's contents AND the directory entry are
        fsynced before keep-last-N pruning unlinks older slots, so a
        crash mid-rotation can never leave zero durable slots."""
        events = []
        real_fsync = os.fsync
        real_remove = os.remove

        def spy_fsync(fd):
            events.append(("fsync", "dir" if _fd_is_dir(fd) else "file"))
            return real_fsync(fd)

        def _fd_is_dir(fd):
            import stat

            return stat.S_ISDIR(os.fstat(fd).st_mode)

        def spy_remove(path):
            events.append(("remove", os.path.basename(path)))
            return real_remove(path)

        monkeypatch.setattr(os, "fsync", spy_fsync)
        monkeypatch.setattr(os, "remove", spy_remove)
        store = CheckpointStore(tmp_path, keep_last=1)
        store.save({"x": jnp.ones(2)}, step=1)
        store.save({"x": jnp.full((2,), 2.0)}, step=2)  # prunes step 1
        kinds = [e for e in events if e[0] == "fsync"]
        assert ("fsync", "file") in kinds and ("fsync", "dir") in kinds
        # Rotation's unlink of the old slot happens strictly after the
        # new slot's syncs.
        last_sync = max(i for i, e in enumerate(events) if e[0] == "fsync")
        first_rm = next(i for i, e in enumerate(events) if e[0] == "remove")
        assert first_rm > last_sync, events
        # And the surviving slot is the durable new one.
        state, _, step = store.load_latest(like={"x": jnp.zeros(2)})
        assert step == 2
        np.testing.assert_array_equal(state["x"], [2.0, 2.0])


class TestWithRetries:
    def test_succeeds_after_transient_failures(self):
        sleeps, calls = [], []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        assert with_retries(flaky, retries=3, backoff=0.5, sleep=sleeps.append) == "ok"
        assert len(calls) == 3
        assert sleeps == [0.5, 1.0]  # exponential backoff

    def test_exhausted_retries_reraise(self):
        def always():
            raise OSError("down")

        with pytest.raises(OSError, match="down"):
            with_retries(always, retries=2, backoff=0.0, sleep=lambda _: None)


# ---------------------------------------------------------------------------
# Chunked solvers: equivalence + preemption/resume


def lsqr_problem(rng, m=80, n=10):
    A = jnp.asarray(rng.standard_normal((m, n)))
    B = jnp.asarray(rng.standard_normal((m, 2)))
    return A, B


class TestChunkedEquivalence:
    def test_lsqr_chunked_matches_one_shot(self, rng):
        A, B = lsqr_problem(rng)
        kp = KrylovParams(iter_lim=30, tolerance=1e-12)
        X1, info1 = lsqr(A, B, params=kp)
        X2, info2 = ResilientRunner(
            lsqr_chunked(A, B, params=kp),
            ResilientParams(checkpoint_every=7),
        ).run()
        np.testing.assert_allclose(np.asarray(X1), np.asarray(X2), rtol=1e-12)
        assert int(info1["iterations"]) == int(info2["iterations"])

    def test_cg_chunked_matches_one_shot(self, rng):
        G = rng.standard_normal((30, 12))
        A = jnp.asarray(G.T @ G + 0.5 * np.eye(12))
        b = jnp.asarray(rng.standard_normal(12))
        kp = KrylovParams(iter_lim=40, tolerance=1e-12)
        x1, _ = cg(A, b, params=kp)
        x2, _ = ResilientRunner(
            cg_chunked(A, b, params=kp), ResilientParams(checkpoint_every=6)
        ).run()
        np.testing.assert_allclose(np.asarray(x1), np.asarray(x2), rtol=1e-12)

    def test_svd_chunked_matches_one_shot(self, rng):
        A = jnp.asarray(rng.standard_normal((48, 16)))
        params = SVDParams(num_iterations=3)
        U1, s1, V1 = approximate_svd(A, 4, SketchContext(seed=5), params)
        U2, s2, V2 = ResilientRunner(
            approximate_svd_chunked(A, 4, SketchContext(seed=5), params),
            ResilientParams(checkpoint_every=1),
        ).run()
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-10)
        np.testing.assert_allclose(np.asarray(U1), np.asarray(U2), rtol=1e-8)


@pytest.mark.faults
class TestPreemptionResume:
    def _run_lsqr(self, A, B, kp, ckdir, plan=None, resume=False):
        return ResilientRunner(
            lsqr_chunked(A, B, params=kp),
            ResilientParams(
                checkpoint_dir=str(ckdir),
                checkpoint_every=5,
                resume=resume,
            ),
            fault_plan=plan,
        ).run()

    def test_lsqr_killed_then_resumed_bit_for_bit(self, tmp_path, rng):
        A, B = lsqr_problem(rng)
        kp = KrylovParams(iter_lim=40, tolerance=1e-13)
        X_ref, info_ref = self._run_lsqr(A, B, kp, tmp_path / "ref")
        # Kill at a random chunk boundary (acceptance: random, but seeded
        # for reproducibility — the guarantee must hold for ANY boundary).
        total_chunks = -(-int(info_ref["iterations"]) // 5)
        kill_at = int(rng.integers(0, max(total_chunks - 1, 1)))
        with pytest.raises(SimulatedPreemption):
            self._run_lsqr(
                A, B, kp, tmp_path / "ck",
                plan=FaultPlan(preempt_after_chunk=kill_at),
            )
        assert CheckpointStore(tmp_path / "ck").steps()  # something committed
        X_res, info_res = self._run_lsqr(A, B, kp, tmp_path / "ck", resume=True)
        assert bits(X_ref) == bits(X_res)
        assert int(info_ref["iterations"]) == int(info_res["iterations"])

    def test_lsqr_corrupt_newest_recovers_from_previous_slot(self, tmp_path, rng):
        A, B = lsqr_problem(rng)
        kp = KrylovParams(iter_lim=40, tolerance=1e-13)
        X_ref, _ = self._run_lsqr(A, B, kp, tmp_path / "ref")
        # Preempt after the second committed chunk so two rotation slots
        # exist on disk.
        with pytest.raises(SimulatedPreemption):
            self._run_lsqr(
                A, B, kp, tmp_path / "ck",
                plan=FaultPlan(preempt_after_chunk=1),
            )
        store = CheckpointStore(tmp_path / "ck")
        steps = store.steps()
        assert len(steps) >= 2
        corrupt_checkpoint(os.path.join(str(tmp_path / "ck"), f"ckpt-{steps[-1]:012d}.npz"))
        # Resume must fall back to the previous rotation slot and still
        # reproduce the uninterrupted run bit-for-bit (chunk boundaries
        # are multiples of K, so the replayed segments are identical).
        X_res, _ = self._run_lsqr(A, B, kp, tmp_path / "ck", resume=True)
        assert bits(X_ref) == bits(X_res)

    def _admm_chunked(self, X, y, seed=11):
        ctx = SketchContext(seed=seed)
        k = GaussianKernel(4, 2.0)
        maps = [k.create_rft(32, "regular", ctx) for _ in range(2)]
        solver = BlockADMMSolver(
            "squared", "l2", maps,
            ADMMParams(rho=1.0, lam=0.01, maxiter=8),
        )
        return solver.chunked(X, y)

    def test_admm_killed_then_resumed_bit_for_bit(self, tmp_path, rng):
        X = rng.standard_normal((32, 4))
        y = np.array([1, 2] * 16)

        def run(ckdir, plan=None, resume=False):
            return ResilientRunner(
                self._admm_chunked(X, y),
                ResilientParams(
                    checkpoint_dir=str(ckdir), checkpoint_every=3,
                    resume=resume,
                ),
                fault_plan=plan,
            ).run()

        m_ref = run(tmp_path / "ref")
        kill_at = int(rng.integers(0, 2))
        with pytest.raises(SimulatedPreemption):
            run(tmp_path / "ck", plan=FaultPlan(preempt_after_chunk=kill_at))
        m_res = run(tmp_path / "ck", resume=True)
        assert bits(m_ref.W) == bits(m_res.W)
        np.testing.assert_array_equal(m_ref.history, m_res.history)

    def test_svd_killed_then_resumed_bit_for_bit(self, tmp_path, rng):
        A = jnp.asarray(rng.standard_normal((48, 16)))
        params = SVDParams(num_iterations=4)

        def run(ckdir, plan=None, resume=False):
            return ResilientRunner(
                approximate_svd_chunked(A, 4, SketchContext(seed=5), params),
                ResilientParams(
                    checkpoint_dir=str(ckdir), checkpoint_every=2,
                    resume=resume,
                ),
                fault_plan=plan,
            ).run()

        U_ref, s_ref, V_ref = run(tmp_path / "ref")
        with pytest.raises(SimulatedPreemption):
            run(tmp_path / "ck", plan=FaultPlan(preempt_after_chunk=0))
        U_res, s_res, V_res = run(tmp_path / "ck", resume=True)
        assert bits(s_ref) == bits(s_res)
        assert bits(U_ref) == bits(U_res)
        assert bits(V_ref) == bits(V_res)

    def test_resume_refuses_foreign_solver_kind(self, tmp_path, rng):
        A, B = lsqr_problem(rng)
        kp = KrylovParams(iter_lim=20)
        with pytest.raises(SimulatedPreemption):
            self._run_lsqr(
                A, B, kp, tmp_path / "ck",
                plan=FaultPlan(preempt_after_chunk=0),
            )
        G = rng.standard_normal((12, 12))
        spd = jnp.asarray(G.T @ G + np.eye(12))
        b = jnp.asarray(rng.standard_normal(12))
        with pytest.raises(CheckpointError, match="solver kind"):
            ResilientRunner(
                cg_chunked(spd, b, params=kp),
                ResilientParams(
                    checkpoint_dir=str(tmp_path / "ck"),
                    checkpoint_every=5, resume=True,
                ),
            ).run()


@pytest.mark.faults
class TestFaultInjection:
    def test_transient_io_errors_are_retried(self, tmp_path, rng):
        A, B = lsqr_problem(rng)
        sleeps = []
        plan = FaultPlan(io_errors_on_save={0: 2})
        X, _ = ResilientRunner(
            lsqr_chunked(A, B, params=KrylovParams(iter_lim=20, tolerance=1e-13)),
            ResilientParams(
                checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=5,
                io_retries=3, io_backoff=0.25,
            ),
            fault_plan=plan,
            sleep=sleeps.append,
        ).run()
        assert plan._save_attempts[0] == 3  # 2 injected failures + success
        assert sleeps[:2] == [0.25, 0.5]
        assert CheckpointStore(tmp_path / "ck").steps()

    def test_io_errors_beyond_retry_budget_raise(self, tmp_path, rng):
        A, B = lsqr_problem(rng)
        with pytest.raises(OSError, match="injected transient"):
            ResilientRunner(
                lsqr_chunked(A, B, params=KrylovParams(iter_lim=20)),
                ResilientParams(
                    checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=5,
                    io_retries=1,
                ),
                fault_plan=FaultPlan(io_errors_on_save={0: 5}),
                sleep=lambda _: None,
            ).run()

    def test_divergence_halts_with_best_iterate(self, rng):
        A, B = lsqr_problem(rng)
        with pytest.raises(ConvergenceError) as exc:
            ResilientRunner(
                lsqr_chunked(A, B, params=KrylovParams(iter_lim=40, tolerance=1e-13)),
                ResilientParams(checkpoint_every=5),
                fault_plan=FaultPlan(nan_after_chunk=1),
            ).run()
        err = exc.value
        assert err.code == 106
        assert err.iteration == 5  # best iterate is the last finite chunk
        X_best, info = err.result
        assert np.isfinite(np.asarray(X_best)).all()

    def test_divergence_unchecked_when_disabled(self, rng):
        A, B = lsqr_problem(rng)
        # With the guard off the poisoned state flows through (documents
        # that check_divergence is what stands between NaN and the caller).
        X, _ = ResilientRunner(
            lsqr_chunked(A, B, params=KrylovParams(iter_lim=12, tolerance=0.0)),
            ResilientParams(checkpoint_every=100, check_divergence=False),
            fault_plan=FaultPlan(nan_after_chunk=0),
        ).run()
        assert not np.isfinite(np.asarray(X)).all()


# ---------------------------------------------------------------------------
# CLI surface


@pytest.mark.faults
class TestResilientCLI:
    def test_skylark_ml_checkpoints_and_resumes(self, tmp_path, rng, capsys):
        from libskylark_tpu.cli.ml import main
        from libskylark_tpu.io import write_libsvm

        X = rng.standard_normal((32, 4))
        y = np.array([1, 2] * 16)
        write_libsvm(tmp_path / "train", X, y)
        args = [
            "--trainfile", str(tmp_path / "train"),
            "--modelfile", str(tmp_path / "m.json"),
            "-f", "64", "-n", "2", "-i", "6",
            "--checkpoint-dir", str(tmp_path / "ck"),
            "--checkpoint-every", "2",
        ]
        assert main(args) == 0
        store = CheckpointStore(tmp_path / "ck")
        assert store.steps()[-1] == 6
        assert (tmp_path / "m.json").exists()
        # Second invocation resumes from the completed checkpoint: no
        # further iterations, same final objective line.
        out1 = capsys.readouterr().out
        assert main(args + ["--resume"]) == 0
        out2 = capsys.readouterr().out
        obj = lambda s: s.split("final objective")[1].split()[0]
        assert obj(out1) == obj(out2)

    def test_skylark_krr_checkpoints(self, tmp_path, rng, capsys):
        from libskylark_tpu.cli.krr import main
        from libskylark_tpu.io import write_libsvm

        X = rng.standard_normal((48, 3))
        y = X.sum(1)
        write_libsvm(tmp_path / "train", X, y)
        rc = main([
            "--trainfile", str(tmp_path / "train"),
            "--modelfile", str(tmp_path / "m.json"),
            "-a", "1", "--regression", "--sigma", "3.0", "-f", "64",
            "--tolerance", "1e-8",
            "--checkpoint-dir", str(tmp_path / "ck"),
            "--checkpoint-every", "10",
        ])
        assert rc == 0
        assert CheckpointStore(tmp_path / "ck").steps()
