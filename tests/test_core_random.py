"""Core RNG invariants: random access, window consistency, distributions.

Mirrors the reference's distributed-vs-local golden-consistency oracle
(`tests/unit/DenseSketchApplyElementalTest.cpp:52-102`): values must be a
pure function of (seed, counter) regardless of how the array is windowed
or sharded.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.stats as st

from libskylark_tpu.core import (
    LeapedHaltonSequence,
    SketchContext,
    primes,
    radical_inverse,
    random_matrix,
    sample,
    sample_window,
)


@pytest.mark.slow
def test_window_matches_full():
    """Any window of the logical array equals the slice of the full array."""
    full = sample_window("normal", seed=7, base=100, full_shape=(32, 17))
    for (r0, c0, r, c) in [(0, 0, 32, 17), (5, 3, 10, 7), (31, 16, 1, 1)]:
        win = sample_window(
            "normal", seed=7, base=100, full_shape=(32, 17),
            offset=(r0, c0), shape=(r, c),
        )
        np.testing.assert_array_equal(np.asarray(win), np.asarray(full[r0:r0 + r, c0:c0 + c]))


@pytest.mark.slow
def test_stream_vs_window():
    """A 1-D stream reshaped row-major equals the 2-D window of same base."""
    stream = sample("uniform", seed=3, base=50, num=6 * 9)
    win = sample_window("uniform", seed=3, base=50, full_shape=(6, 9))
    np.testing.assert_array_equal(np.asarray(stream).reshape(6, 9), np.asarray(win))


@pytest.mark.slow
def test_uniform_cross_dtype_agreement():
    """f32 and f64 uniforms from the same counters agree to ~2^-24: an
    f32 (TPU) run and an f64/native-C run must see the SAME stream (a
    dtype-dependent bit mapping silently breaks cross-language parity —
    found as O(1) prediction differences on hardware)."""
    u32 = np.asarray(sample("uniform", seed=9, base=0, num=4096, dtype=jnp.float32))
    u64 = np.asarray(sample("uniform", seed=9, base=0, num=4096, dtype=jnp.float64))
    assert np.abs(u32 - u64).max() < 2.0 ** -23
    e32 = np.asarray(sample("exponential", seed=9, base=50, num=1024, dtype=jnp.float32))
    e64 = np.asarray(sample("exponential", seed=9, base=50, num=1024, dtype=jnp.float64))
    assert np.abs(e32 - e64).max() / np.abs(e64).max() < 1e-4


@pytest.mark.slow
def test_traced_offset_stream_matches_static():
    """sample(base, offset=traced k) == sample(base+k) — including a
    window whose counters cross the 2^32 carry boundary."""
    import jax

    base = (1 << 32) - 4
    for k in (0, 2, 8):
        static = sample("uniform", seed=11, base=base + k, num=16)
        traced = jax.jit(
            lambda o: sample("uniform", seed=11, base=base, num=16, offset=o)
        )(jnp.uint32(k))
        np.testing.assert_array_equal(np.asarray(static), np.asarray(traced))


def test_disjoint_counters_disjoint_values():
    a = sample("normal", seed=1, base=0, num=100)
    b = sample("normal", seed=1, base=100, num=100)
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_seed_changes_values():
    a = sample("normal", seed=1, base=0, num=100)
    b = sample("normal", seed=2, base=0, num=100)
    assert not np.allclose(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_sharded_generation_bit_identical():
    """Generating under jit with a sharded output == single-device values.

    The counter->bits path must be *bit*-identical across shardings (the
    reference invariant).  Transcendental distribution maps (ndtri etc.) may
    round differently across compiled programs, so values get 1-ulp slack —
    looser than the reference's own 1e-4 oracle (test_utils.hpp:45-53).
    """
    from libskylark_tpu.core import window_bits

    mesh = jax.make_mesh((8,), ("x",))
    spec = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x", None))

    fb = jax.jit(
        lambda: window_bits(11, 77, 16, 0, 0, 64, 16)[0], out_shardings=spec
    )
    np.testing.assert_array_equal(
        np.asarray(fb()), np.asarray(window_bits(11, 77, 16, 0, 0, 64, 16)[0])
    )

    f = jax.jit(
        lambda: sample_window("normal", seed=11, base=77, full_shape=(64, 16)),
        out_shardings=spec,
    )
    np.testing.assert_allclose(
        np.asarray(f()),
        np.asarray(sample_window("normal", seed=11, base=77, full_shape=(64, 16))),
        rtol=3e-7, atol=3e-7,
    )


@pytest.mark.parametrize(
    "dist,params,cdf",
    [
        ("uniform", {}, st.uniform.cdf),
        ("normal", {}, st.norm.cdf),
        ("cauchy", {}, st.cauchy.cdf),
        ("exponential", {}, st.expon.cdf),
        ("levy", {}, st.levy.cdf),
    ],
)
def test_distributions_ks(dist, params, cdf):
    x = np.asarray(sample(dist, seed=5, base=0, num=20000, dtype=jnp.float64, **params))
    assert np.isfinite(x).all()
    stat = st.kstest(x, cdf).pvalue
    assert stat > 1e-4, f"{dist}: KS p-value {stat}"


def test_rademacher():
    x = np.asarray(sample("rademacher", seed=5, base=0, num=10000))
    assert set(np.unique(x)) == {-1.0, 1.0}
    assert abs(x.mean()) < 0.05


def test_uniform_int_range_and_uniformity():
    x = np.asarray(sample("uniform_int", seed=5, base=0, num=50000,
                          dtype=jnp.int32, low=0, high=9))
    assert x.min() == 0 and x.max() == 9
    counts = np.bincount(x, minlength=10)
    assert st.chisquare(counts).pvalue > 1e-4


def test_context_reserve_and_roundtrip():
    ctx = SketchContext(seed=42)
    b0 = ctx.reserve(10)
    b1 = ctx.reserve(5)
    assert (b0, b1, ctx.counter) == (0, 10, 15)
    ctx2 = SketchContext.from_json(ctx.to_json())
    assert ctx2 == ctx
    assert ctx2.reserve(1) == 15


def test_random_matrix_deterministic():
    a = random_matrix(SketchContext(seed=9), (8, 8))
    b = random_matrix(SketchContext(seed=9), (8, 8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_primes():
    np.testing.assert_array_equal(primes(10), [2, 3, 5, 7, 11, 13, 17, 19, 23, 29])


def test_radical_inverse_base2():
    # idx 0 -> value of 1 in base 2 = 0.5; idx 1 -> 2 -> 0.25; idx 2 -> 3 -> 0.75
    vals = np.asarray(radical_inverse(jnp.asarray([2, 2, 2]), jnp.asarray([0, 1, 2])))
    np.testing.assert_allclose(vals, [0.5, 0.25, 0.75])


@pytest.mark.slow
def test_halton_window_matches_coordinate():
    seq = LeapedHaltonSequence(d=4)
    win = np.asarray(seq.window(3, 5, dtype=jnp.float64))
    for r in range(5):
        for c in range(4):
            np.testing.assert_allclose(
                win[r, c], float(seq.coordinate(3 + r, c)), rtol=1e-12
            )


def test_halton_roundtrip():
    seq = LeapedHaltonSequence(d=7)
    seq2 = LeapedHaltonSequence.from_json(seq.to_json())
    assert seq2 == seq


def test_halton_low_discrepancy():
    """QMC sequence should be uniform in [0,1)^d (statistical check)."""
    seq = LeapedHaltonSequence(d=2, leap=1)
    pts = np.asarray(seq.window(0, 2000, dtype=jnp.float64))
    assert st.kstest(pts[:, 0], st.uniform.cdf).pvalue > 1e-4
    assert pts.min() >= 0 and pts.max() < 1


class TestBf16Split3:
    def test_exact_reconstruction(self, rng):
        import jax.numpy as jnp

        from libskylark_tpu.core.precision import bf16_split3

        x = jnp.asarray(
            rng.standard_normal(4096) * 10.0 ** rng.integers(-8, 8, 4096),
            jnp.float32,
        )
        hi, lo, lo2 = bf16_split3(x)
        rec = (np.asarray(hi, np.float64) + np.asarray(lo, np.float64)
               + np.asarray(lo2, np.float64))
        ref = np.asarray(x, np.float64)
        scale = np.maximum(np.abs(ref), 1e-30)
        assert (np.abs(rec - ref) / scale).max() < 2**-22

    def test_rejects_non_f32(self, rng):
        import jax.numpy as jnp
        import pytest

        from libskylark_tpu.core.precision import bf16_split3

        with pytest.raises(TypeError, match="float32"):
            bf16_split3(jnp.arange(4))
