"""Fleet observability plane (ISSUE PR 12): end-to-end request tracing,
the flight recorder, cross-host aggregation, and the exposition surface.

The acceptance contracts pinned here:

- An e2e trace through a coalesced 3-request batch with one poisoned
  request yields THREE complete traces — two sharing the batch-dispatch
  span id, the poisoned one showing the solo-retry rung — and only the
  poisoned one lands in the violation ring.
- ``SKYLARK_TELEMETRY=0`` reruns the same workload bit-identically with
  zero trace allocations (no trace_id in envelopes, empty recorder,
  empty registry).
- ``merge_snapshots`` over per-rank snapshots produces counters EQUAL
  to the per-rank sums, and ``fold_ledgers`` is epoch-fenced exactly
  like the elastic layer.
- Concurrent ``/stats`` + ``/metrics`` + ``/traces`` scrapes during
  live traffic never block the worker and never observe a torn
  snapshot.
"""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

from libskylark_tpu import serve, telemetry
from libskylark_tpu.core.context import SketchContext
from libskylark_tpu.telemetry.trace import FlightRecorder, is_violating
from libskylark_tpu.utils import exceptions as ex

pytestmark = pytest.mark.trace

M, N = 64, 5
_rng = np.random.default_rng(4321)
A = _rng.standard_normal((M, N))
RHS = [_rng.standard_normal(M) for _ in range(6)]


@pytest.fixture
def traced(monkeypatch, tmp_path):
    monkeypatch.setenv("SKYLARK_TELEMETRY", "1")
    monkeypatch.delenv("SKYLARK_TRACE", raising=False)
    telemetry.configure(tmp_path)
    telemetry.reset()
    telemetry.drain_traces()
    yield tmp_path
    telemetry.close()
    telemetry.configure(None)
    telemetry.reset()
    telemetry.drain_traces()


def _ls_server(max_coalesce=8, max_queue=256, deadline_ms=None):
    srv = serve.Server(
        serve.ServeParams(
            max_coalesce=max_coalesce,
            max_queue=max_queue,
            default_deadline_ms=deadline_ms,
            warm_start=False,
            prime=False,
        ),
        seed=42,
    )
    srv.registry.register_system("sys", A, context=SketchContext(seed=9))
    return srv


def _coalesced_poisoned_run():
    """3 requests queued before the worker starts (one coalesced batch);
    request 1 carries a NaN payload."""
    srv = _ls_server()
    reqs = [
        serve.make_request("ls_solve", system="sys", b=b.copy())
        for b in RHS[:3]
    ]
    reqs[1]["b"][3] = float("nan")
    futures = [srv.submit(r) for r in reqs]
    srv.start()
    results = [f.result() for f in futures]
    srv.stop()
    return results


# ---------------------------------------------------------------------------
# the e2e acceptance trace


def test_e2e_coalesced_batch_with_poison_yields_three_traces(traced):
    results = _coalesced_poisoned_run()
    assert [r["ok"] for r in results] == [True, False, True]

    tids = [r["trace"]["trace_id"] for r in results]
    assert len(set(tids)) == 3
    traces = [telemetry.get_trace(t) for t in tids]
    assert all(t is not None for t in traces), "all 3 traces complete"
    assert [t["status"] for t in traces] == ["ok", "error", "ok"]

    # the two healthy requests rode ONE batch dispatch: same span id
    span = lambda t: [  # noqa: E731
        e for e in t["events"] if e["kind"] == "dispatch"
    ]
    d0, d2 = span(traces[0]), span(traces[2])
    assert len(d0) == 1 and len(d2) == 1
    assert d0[0]["span"] == d2[0]["span"]
    assert d0[0]["batch_size"] == 3
    assert set(d0[0]["peers"]) == set(tids)

    # the poisoned one shows the solo-retry rung: the shared dispatch,
    # a fallback, then a FRESH solo dispatch span of batch_size 1
    d1 = span(traces[1])
    assert len(d1) == 2
    assert d1[0]["span"] == d0[0]["span"] and d1[0]["batch_size"] == 3
    assert d1[1]["span"] != d0[0]["span"] and d1[1]["batch_size"] == 1
    kinds = [e["kind"] for e in traces[1]["events"]]
    assert "fallback" in kinds
    errors = [e for e in traces[1]["events"] if e["kind"] == "error"]
    assert errors and errors[-1]["code"] == 108
    assert traces[1]["code"] == 108

    # only the poisoned trace is an SLO violation
    ids = telemetry.trace_ids()
    assert set(ids["recent"]) == set(tids)
    assert ids["violations"] == [tids[1]]

    # violating traces are dumped to the run ledger the moment they
    # finish (post-mortems need no live process)
    telemetry.flush()
    ledger = [
        json.loads(line)
        for line in open(telemetry.ledger_path(), encoding="utf-8")
    ]
    dumped = [r for r in ledger if r["kind"] == "trace"]
    assert [r["attrs"]["trace_id"] for r in dumped] == [tids[1]]


def test_disabled_telemetry_rerun_is_bit_identical_and_traceless(
    traced, monkeypatch
):
    on = _coalesced_poisoned_run()
    telemetry.drain_traces()
    telemetry.reset()
    monkeypatch.setenv("SKYLARK_TELEMETRY", "0")
    off = _coalesced_poisoned_run()

    assert [r["ok"] for r in off] == [True, False, True]
    for r_on, r_off in zip(on, off):
        if r_on["ok"]:  # bit-identical results, traced or not
            assert (
                np.asarray(r_off["result"]) == np.asarray(r_on["result"])
            ).all()
    # zero trace allocations: no ids minted, nothing recorded, nothing
    # counted
    assert all("trace_id" not in r["trace"] for r in off)
    assert len(telemetry.RECORDER) == 0
    assert telemetry.trace_ids() == {"recent": [], "violations": []}
    assert telemetry.REGISTRY.snapshot()["counters"] == {}
    assert telemetry.mint("op") is None


def test_trace_subgate_disables_minting_but_keeps_counters(
    traced, monkeypatch
):
    monkeypatch.setenv("SKYLARK_TRACE", "0")
    results = _coalesced_poisoned_run()
    assert all("trace_id" not in r["trace"] for r in results)
    assert len(telemetry.RECORDER) == 0
    counters = telemetry.REGISTRY.snapshot()["counters"]
    assert counters.get("serve.requests") == 3  # telemetry itself still on
    assert "trace.minted" not in counters


def test_shed_envelopes_carry_queue_state(traced):
    # admission shed: worker not started, queue depth 1 -> second submit
    # sheds at the door with the queue state in its envelope
    srv = _ls_server(max_queue=1)
    reqs = [
        serve.make_request("ls_solve", system="sys", b=b) for b in RHS[:2]
    ]
    f0 = srv.submit(reqs[0])
    r1 = srv.submit(reqs[1]).result()
    assert r1["error"]["code"] == 112
    shed = [
        e for e in r1["trace"]["events"] if e["kind"] == "admission_shed"
    ]
    assert shed and shed[0]["queue_depth"] == 1 and shed[0]["depth"] == 1
    assert shed[0]["requests"] == 2
    errors = [e for e in r1["trace"]["events"] if e["kind"] == "error"]
    assert errors and errors[0]["code"] == 112
    tid = r1["trace"]["trace_id"]
    assert tid in telemetry.trace_ids()["violations"]
    assert telemetry.get_trace(tid)["status"] == "shed_admission"

    # deadline shed: expired before dispatch -> 113 with waited_ms +
    # queue state in the envelope event
    srv2 = _ls_server(deadline_ms=0.001)
    f = srv2.submit(
        serve.make_request("ls_solve", system="sys", b=RHS[0])
    )
    import time

    time.sleep(0.05)
    srv2.start()
    r = f.result()
    srv2.stop()
    assert r["error"]["code"] == 113
    ds = [e for e in r["trace"]["events"] if e["kind"] == "deadline_shed"]
    assert ds and ds[0]["waited_ms"] > 0 and "depth" in ds[0]
    assert telemetry.get_trace(r["trace"]["trace_id"])["status"] == (
        "shed_deadline"
    )
    srv.stop()
    f0.result()


def test_flight_recorder_keeps_all_violations_past_capacity():
    rec = FlightRecorder(capacity=8)
    for i in range(30):
        rec.record({"trace_id": f"ok-{i}", "status": "ok"})
    rec.record({"trace_id": "bad-1", "status": "error"})
    for i in range(30, 60):
        rec.record({"trace_id": f"ok-{i}", "status": "ok"})
    assert len(rec) == 8  # recent ring bounded
    assert rec.get("bad-1") is not None  # ...but the incident survives
    assert rec.ids()["violations"] == ["bad-1"]
    drained = rec.drain()
    assert [p["trace_id"] for p in drained["violations"]] == ["bad-1"]
    assert len(rec) == 0 and rec.get("bad-1") is None


def test_is_violating_flags_retry_and_guard_rungs():
    assert not is_violating([{"kind": "dispatch"}, {"kind": "policy"}])
    assert is_violating([{"kind": "fallback", "reason": "x"}])
    assert is_violating([{"kind": "error", "code": 108}])
    assert not is_violating([{"kind": "guard", "rung": 0}])
    assert is_violating([{"kind": "guard", "rung": 1}])


def test_trace_event_bounded_per_trace(traced):
    tctx = telemetry.mint("op")
    with telemetry.activate([tctx]):
        for i in range(200):
            telemetry.trace_event("spam", i=i)
    telemetry.finish_trace(tctx, "ok")
    payload = telemetry.get_trace(tctx.trace_id)
    assert len(payload["events"]) == 64
    assert payload["events_dropped"] == 200 - 64


# ---------------------------------------------------------------------------
# cross-host aggregation


def test_fleet_merge_counters_equal_sum_of_rank_snapshots(traced):
    # two simulated ranks: run disjoint workloads, snapshot each
    per_rank = []
    for rank in range(2):
        telemetry.reset()
        for _ in range(rank + 1):
            telemetry.inc("serve.requests")
            telemetry.inc("serve.ok")
        telemetry.observe("serve.latency_ms", 10.0 * (rank + 1))
        per_rank.append(telemetry.snapshot())
    merged = telemetry.merge_snapshots(per_rank)
    assert merged["world"] == 2
    for key in ("serve.requests", "serve.ok"):
        assert merged["counters"][key] == sum(
            s["counters"].get(key, 0) for s in per_rank
        )
    h = merged["histograms"]["serve.latency_ms"]
    assert h["count"] == 2 and h["sum"] == 30.0
    assert h["min"] == 10.0 and h["max"] == 20.0
    assert merged["serve"]["requests"] == 3

    # single-process world: snapshot(fleet=True) degenerates to local
    telemetry.reset()
    telemetry.inc("serve.requests", 7)
    fleet = telemetry.snapshot(fleet=True)
    assert fleet["world"] == 1
    assert fleet["counters"]["serve.requests"] == 7


def test_fold_ledgers_is_epoch_fenced(tmp_path):
    from libskylark_tpu.streaming import elastic

    # epoch-0 records from two hosts, then a repartitioned epoch-1 world
    # where only rank 0 survived — only epoch 1 may fold
    for epoch, ranks, rows in ((0, (0, 1), 100), (1, (0,), 250)):
        for rank in ranks:
            d = elastic.host_dir(tmp_path, rank, epoch)
            os.makedirs(d, exist_ok=True)
            led = elastic.HostLedger(
                os.path.join(d, elastic.PROGRESS_NAME),
                rank=rank,
                epoch=epoch,
            )
            for b in range(3):
                led.record("fold", rows=rows, batches=1)
            led.close()
    fold = telemetry.fold_ledgers(tmp_path)
    assert fold["epoch"] == 1  # newest epoch observed wins
    assert set(fold["ranks"]) == {0}
    assert fold["rows_total"] == 3 * 250
    assert fold["stale_records"] == 6  # both epoch-0 hosts fenced out
    assert all(
        rec["attrs"]["epoch"] == 1 for rec in fold["timeline"]
    )

    # empty root folds to an empty view, never raises (the exposition
    # surface stays up before any elastic run writes here)
    empty = telemetry.fold_ledgers(tmp_path / "nothing")
    assert empty["ranks"] == {} and empty["rows_total"] == 0


# ---------------------------------------------------------------------------
# exposition surface


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as fh:
        return fh.getcode(), fh.headers.get("Content-Type"), fh.read()


def test_concurrent_scrapes_during_live_traffic(traced):
    srv = _ls_server().start()
    httpd = serve.serve_http(srv)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"

    failures = []
    stop = threading.Event()

    def scrape(path, check):
        while not stop.is_set():
            try:
                code, _, body = _get(base, path)
                assert code == 200
                check(body)
            except Exception as e:  # noqa: BLE001 — collected, not raised
                failures.append((path, repr(e)))
                return

    def check_metrics(body):
        # never torn: every exposed line is a comment or "name value"
        for line in body.decode().splitlines():
            assert line.startswith("#") or (
                len(line.split()) == 2 and line.startswith("skylark_")
            ), line

    scrapers = [
        threading.Thread(
            target=scrape, args=("/metrics", check_metrics), daemon=True
        ),
        threading.Thread(
            target=scrape,
            args=("/stats", lambda b: json.loads(b)["counters"]),
            daemon=True,
        ),
        threading.Thread(
            target=scrape,
            args=("/traces", lambda b: json.loads(b)["recent"]),
            daemon=True,
        ),
    ]
    for t in scrapers:
        t.start()
    # live traffic while the scrapers hammer the surface
    results = [
        srv.call(serve.make_request("ls_solve", system="sys", b=b))
        for b in RHS * 3
    ]
    stop.set()
    for t in scrapers:
        t.join(timeout=10)
    httpd.shutdown()
    srv.stop()
    assert not failures, failures
    assert all(r["ok"] for r in results)  # scrapes never blocked serving

    snap = telemetry.snapshot()
    assert snap["serve"]["requests"] == len(results)


def test_healthz_metrics_and_trace_endpoints(traced):
    srv = _ls_server().start()
    httpd = serve.serve_http(srv)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"

    r = srv.call(serve.make_request("ls_solve", system="sys", b=RHS[0]))
    tid = r["trace"]["trace_id"]

    code, _, body = _get(base, "/healthz")
    health = json.loads(body)
    assert health["ok"] is True
    assert health["backend"] == "cpu"  # the RESOLVED backend tag
    assert health["registry"] == {"models": 0, "systems": 1}
    assert health["worker_alive"] is True and health["telemetry"] is True

    code, ctype, body = _get(base, "/metrics")
    assert ctype.startswith("text/plain")
    text = body.decode()
    assert "# TYPE skylark_serve_requests_total counter" in text
    assert "skylark_serve_queue_depth 0" in text
    assert "skylark_trace_minted_total 1" in text

    code, _, body = _get(base, f"/traces/{tid}")
    assert json.loads(body)["status"] == "ok"
    code, _, body = _get(base, "/traces")
    assert json.loads(body)["recent"] == [tid]
    code, _, body = _get(base, "/traces?drain=1")
    assert [p["trace_id"] for p in json.loads(body)["recent"]] == [tid]
    assert telemetry.trace_ids()["recent"] == []  # drained through HTTP

    try:
        urllib.request.urlopen(base + "/traces/nope", timeout=10)
        raise AssertionError("unknown trace must 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404

    httpd.shutdown()
    srv.stop()


def test_skylark_top_renders_fleet_frame(tmp_path, capsys):
    from libskylark_tpu.cli import top
    from libskylark_tpu.streaming import elastic

    d = elastic.host_dir(tmp_path, 0, 0)
    os.makedirs(d, exist_ok=True)
    led = elastic.HostLedger(
        os.path.join(d, elastic.PROGRESS_NAME), rank=0, epoch=0
    )
    led.record("fold", rows=500, batches=1)
    led.close()
    rc = top.main(["--root", str(tmp_path), "--once"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "rank   0" in out and "rows        500" in out


def test_error_response_envelopes_unchanged_for_protocol_peers():
    """The trace plane may only ADD envelope fields: a 112 from a
    telemetry-off server still round-trips through the protocol codec
    exactly as PR-10 shipped it."""
    exc = ex.AdmissionError("full", queue_depth=4, max_depth=4)
    frame = serve.encode(serve.error_response("r1", exc, {"events": []}))
    back = serve.exception_for(serve.decode(frame)["error"])
    assert type(back) is ex.AdmissionError and back.code == 112
